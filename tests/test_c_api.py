"""C ABI tests (reference `include/mxnet/c_api.h` principle — §2.3: one C
boundary for all language bindings). Two scenarios:

1. ctypes in-process: the library attaches to THIS interpreter and shares
   its runtime/handles (how the reference's own Python frontend crosses
   the boundary).
2. standalone C host: a compiled C program boots the runtime itself via
   MXTpuInit — the R/Scala/Julia-binding scenario.
"""
import ctypes
import os
import pathlib
import subprocess

import numpy as onp
import pytest

from _capi_testlib import REPO, LIB, built

pytestmark = pytest.mark.skipif(not built(),
                                reason="libmxtpu_c.so not built")


@pytest.fixture(scope="module")
def capi():
    lib = ctypes.CDLL(str(LIB))
    c = ctypes
    lib.MXGetLastError.restype = c.c_char_p
    lib.MXTpuInit.argtypes = [c.c_char_p]
    lib.MXGetVersion.argtypes = [c.POINTER(c.c_int)]
    lib.MXNDArrayCreate.argtypes = [c.POINTER(c.c_int64), c.c_int,
                                    c.c_char_p, c.POINTER(c.c_void_p)]
    lib.MXNDArrayFree.argtypes = [c.c_void_p]
    lib.MXNDArrayGetShape.argtypes = [c.c_void_p, c.POINTER(c.c_int),
                                      c.POINTER(c.c_int64), c.c_int]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [c.c_void_p,
                                             c.POINTER(c.c_float),
                                             c.c_int64]
    lib.MXNDArraySyncCopyToCPU.argtypes = [c.c_void_p,
                                           c.POINTER(c.c_float), c.c_int64]
    lib.MXImperativeInvoke.argtypes = [c.c_char_p, c.POINTER(c.c_void_p),
                                       c.c_int, c.c_char_p,
                                       c.POINTER(c.c_void_p),
                                       c.POINTER(c.c_int)]
    lib.MXListAllOpNames.argtypes = [c.POINTER(c.c_int),
                                     c.POINTER(c.POINTER(c.c_char_p))]
    assert lib.MXTpuInit(None) == 0, lib.MXGetLastError()
    return lib


def test_version_and_ops(capi):
    v = ctypes.c_int()
    assert capi.MXGetVersion(ctypes.byref(v)) == 0
    assert v.value >= 100  # 10000*maj + 100*min + patch (0.1.0 -> 100)
    n = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert capi.MXListAllOpNames(ctypes.byref(n), ctypes.byref(names)) == 0
    assert n.value > 400
    seen = {names[i].decode() for i in range(min(n.value, 2000))}
    assert "relu" in seen and "Convolution" in seen


def test_ndarray_roundtrip_and_invoke(capi):
    shape = (ctypes.c_int64 * 2)(2, 2)
    h = ctypes.c_void_p()
    assert capi.MXNDArrayCreate(shape, 2, b"float32",
                                ctypes.byref(h)) == 0
    src = (ctypes.c_float * 4)(-1.0, 2.0, -3.0, 4.0)
    assert capi.MXNDArraySyncCopyFromCPU(h, src, 4) == 0

    outs = (ctypes.c_void_p * 2)()
    n_out = ctypes.c_int(2)
    assert capi.MXImperativeInvoke(b"relu", ctypes.byref(h), 1, None,
                                   outs, ctypes.byref(n_out)) == 0
    assert n_out.value == 1
    dst = (ctypes.c_float * 4)()
    assert capi.MXNDArraySyncCopyToCPU(outs[0], dst, 4) == 0
    onp.testing.assert_allclose(list(dst), [0.0, 2.0, 0.0, 4.0])

    ndim = ctypes.c_int()
    oshape = (ctypes.c_int64 * 8)()
    assert capi.MXNDArrayGetShape(outs[0], ctypes.byref(ndim), oshape, 8) == 0
    assert ndim.value == 2 and oshape[0] == 2 and oshape[1] == 2

    capi.MXNDArrayFree(h)
    capi.MXNDArrayFree(outs[0])


def test_invoke_with_kwargs_and_error(capi):
    shape = (ctypes.c_int64 * 2)(2, 3)
    h = ctypes.c_void_p()
    assert capi.MXNDArrayCreate(shape, 2, b"float32", ctypes.byref(h)) == 0
    src = (ctypes.c_float * 6)(1, 2, 3, 4, 5, 6)
    assert capi.MXNDArraySyncCopyFromCPU(h, src, 6) == 0
    outs = (ctypes.c_void_p * 2)()
    n_out = ctypes.c_int(2)
    assert capi.MXImperativeInvoke(b"sum", ctypes.byref(h), 1,
                                   b'{"axis": 0}', outs,
                                   ctypes.byref(n_out)) == 0
    dst = (ctypes.c_float * 3)()
    assert capi.MXNDArraySyncCopyToCPU(outs[0], dst, 3) == 0
    onp.testing.assert_allclose(list(dst), [5.0, 7.0, 9.0])
    capi.MXNDArrayFree(outs[0])

    # unknown op surfaces through MXGetLastError, not a crash
    n_out = ctypes.c_int(2)
    assert capi.MXImperativeInvoke(b"definitely_not_an_op",
                                   ctypes.byref(h), 1, None, outs,
                                   ctypes.byref(n_out)) == -1
    assert b"unknown operator" in capi.MXGetLastError()
    capi.MXNDArrayFree(h)


def _build_and_run(c_name, exe_name, extra_args=(), timeout=600):
    exe = REPO / "lib" / exe_name
    src = REPO / "tests" / "c_api" / c_name
    inc = REPO / "src" / "include"
    r = subprocess.run(
        ["gcc", "-O1", str(src), "-I", str(inc),
         "-L", str(REPO / "lib"), "-lmxtpu_c", "-lm",
         "-Wl,-rpath," + str(REPO / "lib"), "-o", str(exe)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # C host must not dial the TPU tunnel
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([str(exe), str(REPO), *map(str, extra_args)],
                       capture_output=True, text=True, env=env,
                       timeout=timeout)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_standalone_c_host():
    """Compile tests/c_api/host_test.c against the ABI and run it as its
    own process (boots the runtime via MXTpuInit)."""
    out = _build_and_run("host_test.c", "host_test")
    assert "C_API_HOST_OK" in out


def test_c_host_trains_lenet():
    """A pure-C host builds LeNet via the symbol ABI, binds an executor,
    trains with sgd_update, kvstore round-trips a weight, and exports the
    model (reference c_api_executor.cc + c_api.cc:986 capability proof)."""
    out = _build_and_run("train_lenet.c", "train_lenet")
    assert "C_API_TRAIN_OK" in out


def test_c_host_predict_exported_model(tmp_path):
    """A pure-C host loads the model the training host exported and runs
    inference through the predict ABI (reference c_predict_api.cc).
    Always regenerates the export so stale artifacts can't mask a
    save/export regression."""
    out = _build_and_run("train_lenet.c", "train_lenet",
                         extra_args=[tmp_path])
    assert "C_API_TRAIN_OK" in out
    out = _build_and_run("predict_host.c", "predict_host",
                         extra_args=[tmp_path / "lenet_capi-symbol.json",
                                     tmp_path / "lenet_capi.params"])
    assert "C_API_PREDICT_OK" in out


def _sig(lib):
    c = ctypes
    sigs = {
        "MXRandomSeed": [c.c_int],
        "MXGetGPUCount": [c.POINTER(c.c_int)],
        "MXLibInfoFeatures": [c.POINTER(c.POINTER(c.c_char_p)),
                              c.POINTER(c.POINTER(c.c_int)),
                              c.POINTER(c.c_int)],
        "MXNDArrayCreateEx": [c.POINTER(c.c_int64), c.c_int, c.c_char_p,
                              c.c_char_p, c.POINTER(c.c_void_p)],
        "MXNDArrayGetDType": [c.c_void_p, c.POINTER(c.c_char_p)],
        "MXNDArrayGetContext": [c.c_void_p, c.POINTER(c.c_char_p)],
        "MXNDArrayReshape": [c.c_void_p, c.c_int, c.POINTER(c.c_int64),
                             c.POINTER(c.c_void_p)],
        "MXNDArraySlice": [c.c_void_p, c.c_int64, c.c_int64,
                           c.POINTER(c.c_void_p)],
        "MXNDArraySave": [c.c_char_p, c.c_int, c.POINTER(c.c_void_p),
                          c.POINTER(c.c_char_p)],
        "MXNDArrayLoad": [c.c_char_p, c.POINTER(c.c_int),
                          c.POINTER(c.POINTER(c.c_void_p)),
                          c.POINTER(c.c_int),
                          c.POINTER(c.POINTER(c.c_char_p))],
        "MXAutogradSetIsRecording": [c.c_int, c.POINTER(c.c_int)],
        "MXAutogradMarkVariables": [c.c_int, c.POINTER(c.c_void_p),
                                    c.POINTER(c.c_int),
                                    c.POINTER(c.c_void_p)],
        "MXAutogradBackward": [c.c_int, c.POINTER(c.c_void_p),
                               c.POINTER(c.c_void_p), c.c_int],
        "MXNDArrayGetGrad": [c.c_void_p, c.POINTER(c.c_void_p)],
        "MXListDataIters": [c.POINTER(c.c_int),
                            c.POINTER(c.POINTER(c.c_char_p))],
        "MXDataIterCreateIter": [c.c_char_p, c.c_int,
                                 c.POINTER(c.c_char_p),
                                 c.POINTER(c.c_char_p),
                                 c.POINTER(c.c_void_p)],
        "MXDataIterNext": [c.c_void_p, c.POINTER(c.c_int)],
        "MXDataIterGetData": [c.c_void_p, c.POINTER(c.c_void_p)],
        "MXDataIterFree": [c.c_void_p],
        "MXRecordIOWriterCreate": [c.c_char_p, c.POINTER(c.c_void_p)],
        "MXRecordIOWriterWriteRecord": [c.c_void_p, c.c_char_p, c.c_int64],
        "MXRecordIOWriterFree": [c.c_void_p],
        "MXRecordIOReaderCreate": [c.c_char_p, c.POINTER(c.c_void_p)],
        "MXRecordIOReaderReadRecord": [c.c_void_p, c.POINTER(c.c_char_p),
                                       c.POINTER(c.c_int64)],
        "MXRecordIOReaderFree": [c.c_void_p],
    }
    for name, argtypes in sigs.items():
        getattr(lib, name).argtypes = argtypes
    return lib


def test_ndarray_extended_abi(capi, tmp_path):
    c = ctypes
    lib = _sig(capi)
    assert lib.MXRandomSeed(42) == 0
    n = c.c_int()
    assert lib.MXGetGPUCount(c.byref(n)) == 0 and n.value >= 1

    names = c.POINTER(c.c_char_p)()
    flags = c.POINTER(c.c_int)()
    sz = c.c_int()
    assert lib.MXLibInfoFeatures(c.byref(names), c.byref(flags),
                                 c.byref(sz)) == 0
    assert sz.value > 5

    shape = (c.c_int64 * 2)(4, 6)
    h = c.c_void_p()
    assert lib.MXNDArrayCreateEx(shape, 2, b"float32", b"cpu",
                                 c.byref(h)) == 0
    dt = c.c_char_p()
    assert lib.MXNDArrayGetDType(h, c.byref(dt)) == 0
    assert dt.value == b"float32"
    cx = c.c_char_p()
    assert lib.MXNDArrayGetContext(h, c.byref(cx)) == 0
    assert cx.value == b"cpu(0)"

    h2 = c.c_void_p()
    dims = (c.c_int64 * 2)(6, 4)
    assert lib.MXNDArrayReshape(h, 2, dims, c.byref(h2)) == 0
    nd = c.c_int()
    shp = (c.c_int64 * 8)()
    assert capi.MXNDArrayGetShape(h2, c.byref(nd), shp, 8) == 0
    assert (shp[0], shp[1]) == (6, 4)

    h3 = c.c_void_p()
    assert lib.MXNDArraySlice(h, 1, 3, c.byref(h3)) == 0
    assert capi.MXNDArrayGetShape(h3, c.byref(nd), shp, 8) == 0
    assert (shp[0], shp[1]) == (2, 6)

    # save / load named container
    fname = str(tmp_path / "x.params").encode()
    keys = (c.c_char_p * 1)(b"arg:w")
    arrs = (c.c_void_p * 1)(h)
    assert lib.MXNDArraySave(fname, 1, arrs, keys) == 0
    n_out, n_names = c.c_int(), c.c_int()
    out_arrs = c.POINTER(c.c_void_p)()
    out_names = c.POINTER(c.c_char_p)()
    assert lib.MXNDArrayLoad(fname, c.byref(n_out), c.byref(out_arrs),
                             c.byref(n_names), c.byref(out_names)) == 0
    assert n_out.value == 1 and out_names[0] == b"arg:w"
    capi.MXNDArrayFree(out_arrs[0])
    for hh in (h, h2, h3):
        capi.MXNDArrayFree(hh)


def test_autograd_abi(capi):
    c = ctypes
    lib = _sig(capi)
    shape = (c.c_int64 * 1)(3,)
    x = c.c_void_p()
    assert capi.MXNDArrayCreate(shape, 1, b"float32", c.byref(x)) == 0
    src = (c.c_float * 3)(1.0, 2.0, 3.0)
    assert capi.MXNDArraySyncCopyFromCPU(x, src, 3) == 0
    g = c.c_void_p()
    assert capi.MXNDArrayCreate(shape, 1, b"float32", c.byref(g)) == 0

    prev = c.c_int()
    assert lib.MXAutogradSetIsRecording(1, c.byref(prev)) == 0
    reqs = (c.c_int * 1)(1)
    vars_ = (c.c_void_p * 1)(x)
    grads = (c.c_void_p * 1)(g)
    assert lib.MXAutogradMarkVariables(1, vars_, reqs, grads) == 0

    # y = x * x under the tape
    outs = (c.c_void_p * 1)()
    n_out = c.c_int(1)
    ins = (c.c_void_p * 2)(x, x)
    assert capi.MXImperativeInvoke(b"elemwise_mul", ins, 2, None, outs,
                                   c.byref(n_out)) == 0
    assert lib.MXAutogradBackward(1, outs, None, 0) == 0
    assert lib.MXAutogradSetIsRecording(0, c.byref(prev)) == 0

    gh = c.c_void_p()
    assert lib.MXNDArrayGetGrad(x, c.byref(gh)) == 0
    dst = (c.c_float * 3)()
    assert capi.MXNDArraySyncCopyToCPU(gh, dst, 3) == 0
    onp.testing.assert_allclose(list(dst), [2.0, 4.0, 6.0], rtol=1e-5)
    for hh in (x, g, outs[0], gh):
        capi.MXNDArrayFree(hh)


def test_dataiter_and_recordio_abi(capi, tmp_path):
    c = ctypes
    lib = _sig(capi)

    # recordio round-trip
    uri = str(tmp_path / "t.rec").encode()
    w = c.c_void_p()
    assert lib.MXRecordIOWriterCreate(uri, c.byref(w)) == 0
    assert lib.MXRecordIOWriterWriteRecord(w, b"hello", 5) == 0
    assert lib.MXRecordIOWriterWriteRecord(w, b"worlds!", 7) == 0
    assert lib.MXRecordIOWriterFree(w) == 0
    r = c.c_void_p()
    assert lib.MXRecordIOReaderCreate(uri, c.byref(r)) == 0
    buf = c.c_char_p()
    nbytes = c.c_int64()
    assert lib.MXRecordIOReaderReadRecord(r, c.byref(buf),
                                          c.byref(nbytes)) == 0
    assert ctypes.string_at(buf, nbytes.value) == b"hello"
    assert lib.MXRecordIOReaderReadRecord(r, c.byref(buf),
                                          c.byref(nbytes)) == 0
    assert ctypes.string_at(buf, nbytes.value) == b"worlds!"
    assert lib.MXRecordIOReaderReadRecord(r, c.byref(buf),
                                          c.byref(nbytes)) == 0
    assert nbytes.value == -1  # EOF
    assert lib.MXRecordIOReaderFree(r) == 0

    # CSVIter through the C iterator ABI
    csv = tmp_path / "d.csv"
    csv.write_text("\n".join(
        ",".join(str(i * 4 + j) for j in range(4)) for i in range(6)))
    n = c.c_int()
    names = c.POINTER(c.c_char_p)()
    assert lib.MXListDataIters(c.byref(n), c.byref(names)) == 0
    listed = {names[i] for i in range(n.value)}
    assert b"CSVIter" in listed
    keys = (c.c_char_p * 3)(b"data_csv", b"data_shape", b"batch_size")
    vals = (c.c_char_p * 3)(str(csv).encode(), b"(4,)", b"2")
    it = c.c_void_p()
    assert lib.MXDataIterCreateIter(b"CSVIter", 3, keys, vals,
                                    c.byref(it)) == 0, capi.MXGetLastError()
    more = c.c_int()
    assert lib.MXDataIterNext(it, c.byref(more)) == 0 and more.value == 1
    d = c.c_void_p()
    assert lib.MXDataIterGetData(it, c.byref(d)) == 0
    nd = c.c_int()
    shp = (c.c_int64 * 4)()
    assert capi.MXNDArrayGetShape(d, c.byref(nd), shp, 4) == 0
    assert (shp[0], shp[1]) == (2, 4)
    host = (c.c_float * 8)()
    assert capi.MXNDArraySyncCopyToCPU(d, host, 8) == 0
    onp.testing.assert_allclose(list(host), list(range(8)))
    capi.MXNDArrayFree(d)
    assert lib.MXDataIterFree(it) == 0
