"""C ABI tests (reference `include/mxnet/c_api.h` principle — §2.3: one C
boundary for all language bindings). Two scenarios:

1. ctypes in-process: the library attaches to THIS interpreter and shares
   its runtime/handles (how the reference's own Python frontend crosses
   the boundary).
2. standalone C host: a compiled C program boots the runtime itself via
   MXTpuInit — the R/Scala/Julia-binding scenario.
"""
import ctypes
import os
import pathlib
import subprocess

import numpy as onp
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
LIB = REPO / "lib" / "libmxtpu_c.so"


def _built():
    if LIB.exists():
        return True
    r = subprocess.run(["make", "-C", str(REPO / "src")],
                       capture_output=True, text=True)
    return r.returncode == 0 and LIB.exists()


pytestmark = pytest.mark.skipif(not _built(),
                                reason="libmxtpu_c.so not built")


@pytest.fixture(scope="module")
def capi():
    lib = ctypes.CDLL(str(LIB))
    c = ctypes
    lib.MXGetLastError.restype = c.c_char_p
    lib.MXTpuInit.argtypes = [c.c_char_p]
    lib.MXGetVersion.argtypes = [c.POINTER(c.c_int)]
    lib.MXNDArrayCreate.argtypes = [c.POINTER(c.c_int64), c.c_int,
                                    c.c_char_p, c.POINTER(c.c_void_p)]
    lib.MXNDArrayFree.argtypes = [c.c_void_p]
    lib.MXNDArrayGetShape.argtypes = [c.c_void_p, c.POINTER(c.c_int),
                                      c.POINTER(c.c_int64), c.c_int]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [c.c_void_p,
                                             c.POINTER(c.c_float),
                                             c.c_int64]
    lib.MXNDArraySyncCopyToCPU.argtypes = [c.c_void_p,
                                           c.POINTER(c.c_float), c.c_int64]
    lib.MXImperativeInvoke.argtypes = [c.c_char_p, c.POINTER(c.c_void_p),
                                       c.c_int, c.c_char_p,
                                       c.POINTER(c.c_void_p),
                                       c.POINTER(c.c_int)]
    lib.MXListAllOpNames.argtypes = [c.POINTER(c.c_int),
                                     c.POINTER(c.POINTER(c.c_char_p))]
    assert lib.MXTpuInit(None) == 0, lib.MXGetLastError()
    return lib


def test_version_and_ops(capi):
    v = ctypes.c_int()
    assert capi.MXGetVersion(ctypes.byref(v)) == 0
    assert v.value >= 100  # 10000*maj + 100*min + patch (0.1.0 -> 100)
    n = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert capi.MXListAllOpNames(ctypes.byref(n), ctypes.byref(names)) == 0
    assert n.value > 400
    seen = {names[i].decode() for i in range(min(n.value, 2000))}
    assert "relu" in seen and "Convolution" in seen


def test_ndarray_roundtrip_and_invoke(capi):
    shape = (ctypes.c_int64 * 2)(2, 2)
    h = ctypes.c_void_p()
    assert capi.MXNDArrayCreate(shape, 2, b"float32",
                                ctypes.byref(h)) == 0
    src = (ctypes.c_float * 4)(-1.0, 2.0, -3.0, 4.0)
    assert capi.MXNDArraySyncCopyFromCPU(h, src, 4) == 0

    outs = (ctypes.c_void_p * 2)()
    n_out = ctypes.c_int(2)
    assert capi.MXImperativeInvoke(b"relu", ctypes.byref(h), 1, None,
                                   outs, ctypes.byref(n_out)) == 0
    assert n_out.value == 1
    dst = (ctypes.c_float * 4)()
    assert capi.MXNDArraySyncCopyToCPU(outs[0], dst, 4) == 0
    onp.testing.assert_allclose(list(dst), [0.0, 2.0, 0.0, 4.0])

    ndim = ctypes.c_int()
    oshape = (ctypes.c_int64 * 8)()
    assert capi.MXNDArrayGetShape(outs[0], ctypes.byref(ndim), oshape, 8) == 0
    assert ndim.value == 2 and oshape[0] == 2 and oshape[1] == 2

    capi.MXNDArrayFree(h)
    capi.MXNDArrayFree(outs[0])


def test_invoke_with_kwargs_and_error(capi):
    shape = (ctypes.c_int64 * 2)(2, 3)
    h = ctypes.c_void_p()
    assert capi.MXNDArrayCreate(shape, 2, b"float32", ctypes.byref(h)) == 0
    src = (ctypes.c_float * 6)(1, 2, 3, 4, 5, 6)
    assert capi.MXNDArraySyncCopyFromCPU(h, src, 6) == 0
    outs = (ctypes.c_void_p * 2)()
    n_out = ctypes.c_int(2)
    assert capi.MXImperativeInvoke(b"sum", ctypes.byref(h), 1,
                                   b'{"axis": 0}', outs,
                                   ctypes.byref(n_out)) == 0
    dst = (ctypes.c_float * 3)()
    assert capi.MXNDArraySyncCopyToCPU(outs[0], dst, 3) == 0
    onp.testing.assert_allclose(list(dst), [5.0, 7.0, 9.0])
    capi.MXNDArrayFree(outs[0])

    # unknown op surfaces through MXGetLastError, not a crash
    n_out = ctypes.c_int(2)
    assert capi.MXImperativeInvoke(b"definitely_not_an_op",
                                   ctypes.byref(h), 1, None, outs,
                                   ctypes.byref(n_out)) == -1
    assert b"unknown operator" in capi.MXGetLastError()
    capi.MXNDArrayFree(h)


def test_standalone_c_host():
    """Compile tests/c_api/host_test.c against the ABI and run it as its
    own process (boots the runtime via MXTpuInit)."""
    exe = REPO / "lib" / "host_test"
    src = REPO / "tests" / "c_api" / "host_test.c"
    inc = REPO / "src" / "include"
    r = subprocess.run(
        ["gcc", "-O1", str(src), "-I", str(inc),
         "-L", str(REPO / "lib"), "-lmxtpu_c",
         "-Wl,-rpath," + str(REPO / "lib"), "-o", str(exe)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # C host must not dial the TPU tunnel
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([str(exe), str(REPO)], capture_output=True,
                       text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "C_API_HOST_OK" in r.stdout
