"""Round-5 operator long-tail port (VERDICT r4 item 5): behaviors from
reference `tests/python/unittest/test_operator.py` edge cases not yet
covered by the oracle/port suites — reshape special codes, zero-size
tensors, grouped/dilated convolution structure, layout shuffles,
introspection, error contracts. Re-implemented against numpy oracles
(no reference code copied)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError


def _r(*shape, seed=0):
    return onp.random.RandomState(seed).uniform(-1, 1, shape).astype("float32")


# ------------------------------------------------ elementwise / arithmetic

def test_elementwise_sum_many():
    arrs = [_r(3, 4, seed=i) for i in range(5)]
    out = nd.ElementWiseSum(*[nd.array(a) for a in arrs])
    onp.testing.assert_allclose(out.asnumpy(), sum(arrs), rtol=1e-6)


def test_add_n_single_and_many():
    a = _r(2, 3)
    onp.testing.assert_allclose(nd.add_n(nd.array(a)).asnumpy(), a)
    out = nd.add_n(nd.array(a), nd.array(a), nd.array(a))
    onp.testing.assert_allclose(out.asnumpy(), 3 * a, rtol=1e-6)


def test_scalar_pow_and_rpow():
    a = _r(3, 3) + 2.0
    onp.testing.assert_allclose((nd.array(a) ** 2.5).asnumpy(),
                                a ** 2.5, rtol=1e-5)
    onp.testing.assert_allclose((2.0 ** nd.array(a)).asnumpy(),
                                2.0 ** a, rtol=1e-5)


def test_symbol_pow_forward_backward():
    from mxnet_tpu import autograd as ag
    a = nd.array(_r(4) + 2.0)
    b = nd.array(_r(4) + 1.5)
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        y = a ** b
    y.backward(nd.ones((4,)))
    an, bn = a.asnumpy(), b.asnumpy()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                bn * an ** (bn - 1), rtol=1e-4)
    onp.testing.assert_allclose(b.grad.asnumpy(),
                                an ** bn * onp.log(an), rtol=1e-4)


def test_maximum_minimum_scalar():
    a = _r(3, 4)
    onp.testing.assert_allclose(nd.maximum(nd.array(a), 0.3).asnumpy(),
                                onp.maximum(a, 0.3))
    onp.testing.assert_allclose(nd.minimum(nd.array(a), -0.1).asnumpy(),
                                onp.minimum(a, -0.1))


def test_binary_op_duplicate_input_grad():
    from mxnet_tpu import autograd as ag
    a = nd.array(_r(3))
    a.attach_grad()
    with ag.record():
        y = (a * a).sum()
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), 2 * a.asnumpy(),
                                rtol=1e-6)


def test_sign_round_ceil_floor_trunc():
    a = onp.array([-2.7, -0.5, 0.0, 0.5, 2.7], "float32")
    for op, ref in (("sign", onp.sign), ("round", onp.round),
                    ("ceil", onp.ceil), ("floor", onp.floor),
                    ("trunc", onp.trunc)):
        onp.testing.assert_allclose(
            getattr(nd, op)(nd.array(a)).asnumpy(), ref(a), err_msg=op)


def test_reciprocal_cbrt_rcbrt():
    a = _r(3, 3) + 2.0
    onp.testing.assert_allclose(nd.reciprocal(nd.array(a)).asnumpy(),
                                1.0 / a, rtol=1e-6)
    onp.testing.assert_allclose(nd.cbrt(nd.array(a)).asnumpy(),
                                onp.cbrt(a), rtol=1e-5)
    onp.testing.assert_allclose(nd.rcbrt(nd.array(a)).asnumpy(),
                                1.0 / onp.cbrt(a), rtol=1e-5)


def test_div_sqrt_dim():
    a = _r(4, 16)
    out = nd._contrib_div_sqrt_dim(nd.array(a))
    onp.testing.assert_allclose(out.asnumpy(), a / onp.sqrt(16.0),
                                rtol=1e-6)


def test_binary_and_unary_logic():
    a = onp.array([[1.0, 0.0], [2.0, 0.0]], "float32")
    b = onp.array([[1.0, 1.0], [0.0, 0.0]], "float32")
    onp.testing.assert_array_equal(
        nd.broadcast_logical_and(nd.array(a), nd.array(b)).asnumpy(),
        onp.logical_and(a, b).astype("float32"))
    onp.testing.assert_array_equal(
        nd.broadcast_logical_or(nd.array(a), nd.array(b)).asnumpy(),
        onp.logical_or(a, b).astype("float32"))
    onp.testing.assert_array_equal(
        nd.broadcast_logical_xor(nd.array(a), nd.array(b)).asnumpy(),
        onp.logical_xor(a, b).astype("float32"))
    onp.testing.assert_array_equal(
        nd.logical_not(nd.array(a)).asnumpy(),
        onp.logical_not(a).astype("float32"))


def test_quadratic_function():
    a = _r(3, 4)
    out = nd._contrib_quadratic(nd.array(a), a=2.0, b=-1.0, c=0.5) \
        if hasattr(nd, "_contrib_quadratic") else None
    if out is None:
        pytest.skip("quadratic not registered")
    onp.testing.assert_allclose(out.asnumpy(), 2 * a * a - a + 0.5,
                                rtol=1e-6)


# ------------------------------------------------------ shape manipulation

def test_reshape_special_codes():
    a = _r(2, 3, 4, 5)
    # 0 copies the input dim; -1 infers; -2 copies the remainder
    assert nd.reshape(nd.array(a), shape=(0, -1)).shape == (2, 60)
    assert nd.reshape(nd.array(a), shape=(0, 0, -1)).shape == (2, 3, 20)
    assert nd.reshape(nd.array(a), shape=(-2,)).shape == (2, 3, 4, 5)
    assert nd.reshape(nd.array(a), shape=(0, -2)).shape == (2, 3, 4, 5)
    # -3 merges two consecutive dims; -4 splits one
    assert nd.reshape(nd.array(a), shape=(-3, 4, 5)).shape == (6, 4, 5)
    assert nd.reshape(nd.array(a), shape=(2, 3, -4, 2, 2, 5)).shape == \
        (2, 3, 2, 2, 5)


def test_reshape_like_different_types():
    a = nd.array(_r(2, 6))
    like = nd.array(onp.zeros((3, 4), "int32").astype("float32"))
    out = nd.reshape_like(a, like)
    assert out.shape == (3, 4)
    onp.testing.assert_allclose(out.asnumpy().reshape(-1),
                                a.asnumpy().reshape(-1))


def test_slice_channel_variants():
    a = _r(2, 6, 4)
    outs = nd.SliceChannel(nd.array(a), num_outputs=3, axis=1)
    assert len(outs) == 3
    for i, o in enumerate(outs):
        onp.testing.assert_allclose(o.asnumpy(), a[:, 2 * i:2 * i + 2, :])
    # squeeze_axis removes the sliced dim when it becomes 1
    outs = nd.SliceChannel(nd.array(a), num_outputs=6, axis=1,
                           squeeze_axis=True)
    assert outs[0].shape == (2, 4)


def test_swapaxes_roundtrip():
    a = _r(2, 3, 4)
    out = nd.SwapAxis(nd.array(a), dim1=0, dim2=2)
    onp.testing.assert_allclose(out.asnumpy(), a.swapaxes(0, 2))
    back = nd.swapaxes(out, 0, 2)
    onp.testing.assert_allclose(back.asnumpy(), a)


def test_shape_and_size_array():
    a = nd.array(_r(3, 5, 2))
    onp.testing.assert_array_equal(nd.shape_array(a).asnumpy(), [3, 5, 2])
    assert int(nd.size_array(a).asnumpy().reshape(())) == 30


def test_expand_dims_and_squeeze():
    a = _r(3, 4)
    e = nd.expand_dims(nd.array(a), axis=1)
    assert e.shape == (3, 1, 4)
    s = nd.squeeze(e, axis=1)
    assert s.shape == (3, 4)
    # squeeze all singleton dims
    b = nd.array(a.reshape(1, 3, 1, 4))
    assert nd.squeeze(b).shape == (3, 4)


def test_flip_axes():
    a = _r(2, 3, 4)
    onp.testing.assert_allclose(nd.flip(nd.array(a), axis=1).asnumpy(),
                                a[:, ::-1, :])
    onp.testing.assert_allclose(nd.reverse(nd.array(a), axis=2).asnumpy(),
                                a[:, :, ::-1])


def test_stack_axes():
    xs = [_r(2, 3, seed=i) for i in range(4)]
    for ax in (0, 1, 2):
        out = nd.stack(*[nd.array(x) for x in xs], axis=ax)
        onp.testing.assert_allclose(out.asnumpy(), onp.stack(xs, axis=ax))


def test_diag_k_offsets():
    a = _r(4, 4)
    for k in (-1, 0, 1, 2):
        onp.testing.assert_allclose(nd.diag(nd.array(a), k=k).asnumpy(),
                                    onp.diag(a, k=k), err_msg=str(k))
    v = _r(5)
    onp.testing.assert_allclose(nd.diag(nd.array(v)).asnumpy(), onp.diag(v))


def test_depthtospace_spacetodepth_roundtrip():
    a = _r(2, 12, 3, 3)
    d = nd.depth_to_space(nd.array(a), block_size=2)
    assert d.shape == (2, 3, 6, 6)
    back = nd.space_to_depth(d, block_size=2)
    onp.testing.assert_allclose(back.asnumpy(), a, rtol=1e-6)


def test_transpose_infer_shape_back():
    # reference: transpose axes compose/invert correctly through symbols
    x = mx.sym.var("x")
    y = mx.sym.transpose(mx.sym.transpose(x, axes=(1, 2, 0)),
                         axes=(2, 0, 1))
    arg, out, _ = y.infer_shape(x=(2, 3, 4))
    assert tuple(out[0]) == (2, 3, 4)


def test_big_transpose_values():
    a = (_r(1, 10, 33, 65) * 100).astype("int32").astype("float32")
    t = nd.transpose(nd.array(a), axes=(0, 3, 1, 2))
    onp.testing.assert_array_equal(t.asnumpy(), a.transpose(0, 3, 1, 2))


def test_ravel_unravel_roundtrip():
    shape = (3, 7, 5)
    idx = onp.array([[0, 2, 1, 2], [1, 6, 0, 3], [4, 0, 2, 1]], "float32")
    flat = nd.ravel_multi_index(nd.array(idx), shape=shape)
    ref = onp.ravel_multi_index(idx.astype("int64"), shape)
    onp.testing.assert_array_equal(flat.asnumpy().astype("int64"), ref)
    back = nd.unravel_index(flat, shape=shape)
    onp.testing.assert_array_equal(back.asnumpy().astype("int64"),
                                   idx.astype("int64"))


def test_index_array_op():
    a = nd.zeros((2, 3))
    out = nd.index_array(a)
    ref = onp.stack(onp.meshgrid(onp.arange(2), onp.arange(3),
                                 indexing="ij"), axis=-1)
    onp.testing.assert_array_equal(out.asnumpy().astype("int64"), ref)


def test_scatter_gather_nd_roundtrip():
    data = nd.array(_r(4, 5))
    idx = nd.array(onp.array([[0, 2, 3], [1, 0, 4]], "float32"))
    picked = nd.gather_nd(data, idx)
    assert picked.shape == (3,)
    scattered = nd.scatter_nd(picked, idx, shape=(4, 5))
    d = data.asnumpy()
    exp = onp.zeros((4, 5), "float32")
    for j in range(3):
        exp[int(idx.asnumpy()[0, j]), int(idx.asnumpy()[1, j])] = \
            d[int(idx.asnumpy()[0, j]), int(idx.asnumpy()[1, j])]
    onp.testing.assert_allclose(scattered.asnumpy(), exp)


# ------------------------------------------------------- zero-size tensors

def test_scalar_tensor_creation():
    a = nd.array(onp.float32(3.5))
    assert a.shape == () or a.shape == (1,)
    assert float(a.asnumpy()) == 3.5


def test_zero_size_tensor_creation_and_ops():
    z = nd.zeros((0, 4))
    assert z.shape == (0, 4)
    assert z.asnumpy().size == 0
    s = nd.sum(z)
    assert float(s.asnumpy()) == 0.0


def test_concat_with_zero_size_tensor():
    a = nd.array(_r(2, 3))
    z = nd.zeros((0, 3))
    out = nd.concat(a, z, nd.array(_r(1, 3, seed=1)), dim=0)
    assert out.shape == (3, 3)


def test_zero_size_min_max():
    z = nd.zeros((0,))
    # min/max of empty: mxnet returns the identity; ours must not crash
    try:
        nd.max(z).asnumpy()
    except (MXNetError, ValueError):
        pass  # either contract is acceptable; no crash


# ------------------------------------------------------------ convolution

def test_convolution_grouping_matches_split():
    a = _r(2, 4, 8, 8)
    w = _r(6, 2, 3, 3, seed=1)
    out = nd.Convolution(nd.array(a), nd.array(w), no_bias=True,
                         kernel=(3, 3), num_filter=6, num_group=2)
    # oracle: run each group separately
    o1 = nd.Convolution(nd.array(a[:, :2]), nd.array(w[:3]), no_bias=True,
                        kernel=(3, 3), num_filter=3)
    o2 = nd.Convolution(nd.array(a[:, 2:]), nd.array(w[3:]), no_bias=True,
                        kernel=(3, 3), num_filter=3)
    ref = onp.concatenate([o1.asnumpy(), o2.asnumpy()], axis=1)
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_depthwise_convolution():
    a = _r(2, 4, 6, 6)
    w = _r(4, 1, 3, 3, seed=2)
    out = nd.Convolution(nd.array(a), nd.array(w), no_bias=True,
                         kernel=(3, 3), num_filter=4, num_group=4)
    for c in range(4):
        ref = nd.Convolution(nd.array(a[:, c:c + 1]),
                             nd.array(w[c:c + 1]), no_bias=True,
                             kernel=(3, 3), num_filter=1)
        onp.testing.assert_allclose(out.asnumpy()[:, c],
                                    ref.asnumpy()[:, 0],
                                    rtol=1e-4, atol=1e-5)


def test_convolution_dilated_impulse_response():
    """A dilated conv's receptive field on an impulse spans
    dilation*(k-1)+1 (reference test_convolution_dilated_impulse_response)."""
    img = onp.zeros((1, 1, 15, 15), "float32")
    img[0, 0, 7, 7] = 1.0
    w = onp.ones((1, 1, 3, 3), "float32")
    for dil in (1, 2, 3):
        out = nd.Convolution(nd.array(img), nd.array(w), no_bias=True,
                             kernel=(3, 3), dilate=(dil, dil),
                             pad=(dil, dil), num_filter=1).asnumpy()
        nz = onp.nonzero(out[0, 0])
        span = nz[0].max() - nz[0].min() + 1
        assert span == 2 * dil + 1, (dil, span)


def test_convolution_independent_gradients():
    """dw for one conv is independent of a parallel conv's weights."""
    from mxnet_tpu import autograd as ag
    x = nd.array(_r(1, 2, 5, 5))
    w1 = nd.array(_r(2, 2, 3, 3, seed=3))
    w2 = nd.array(_r(2, 2, 3, 3, seed=4))
    w1.attach_grad()
    w2.attach_grad()
    with ag.record():
        y = (nd.Convolution(x, w1, no_bias=True, kernel=(3, 3),
                            num_filter=2) +
             nd.Convolution(x, w2, no_bias=True, kernel=(3, 3),
                            num_filter=2)).sum()
    y.backward()
    onp.testing.assert_allclose(w1.grad.asnumpy(), w2.grad.asnumpy(),
                                rtol=1e-5)  # same x, same cotangent


def test_invalid_kernel_size_raises():
    with pytest.raises((MXNetError, ValueError, TypeError, Exception)):
        nd.Pooling(nd.array(_r(1, 1, 4, 4)), kernel=(0, 0),
                   pool_type="max").asnumpy()


def test_valid_kernel_size_boundary():
    out = nd.Pooling(nd.array(_r(1, 1, 4, 4)), kernel=(4, 4),
                     pool_type="max")
    assert out.shape == (1, 1, 1, 1)


# ------------------------------------------------------- upsampling / etc

def test_nearest_upsampling_values():
    a = _r(1, 2, 3, 3)
    out = nd.UpSampling(nd.array(a), scale=2, sample_type="nearest")
    assert out.shape == (1, 2, 6, 6)
    onp.testing.assert_allclose(out.asnumpy(),
                                a.repeat(2, axis=2).repeat(2, axis=3))


def test_bilinear_upsampling_shape_and_corners():
    a = _r(1, 1, 4, 4)
    w = onp.ones((1, 1, 4, 4), "float32")
    out = nd.UpSampling(nd.array(a), nd.array(w), scale=2,
                        sample_type="bilinear", num_filter=1)
    assert out.shape[2] == 8 and out.shape[3] == 8


def test_image_normalize():
    a = onp.random.RandomState(0).uniform(0, 1, (3, 4, 4)).astype("float32")
    from mxnet_tpu.gluon.data.vision import transforms
    t = transforms.Normalize(mean=(0.5, 0.4, 0.3), std=(0.2, 0.2, 0.2))
    out = t(nd.array(a)).asnumpy()
    ref = (a - onp.array([0.5, 0.4, 0.3])[:, None, None]) / 0.2
    onp.testing.assert_allclose(out, ref, rtol=1e-5)


def test_moments_op():
    a = _r(3, 4)
    mean, var = nd.moments(nd.array(a), axes=(0,))
    onp.testing.assert_allclose(mean.asnumpy(), a.mean(0), rtol=1e-5)
    onp.testing.assert_allclose(var.asnumpy(), a.var(0), rtol=1e-4,
                                atol=1e-6)


def test_dropout_axes_broadcast():
    """Dropout with axes shares one mask along the dropped axes."""
    mx.random.seed(3)
    a = nd.ones((4, 8, 8))
    out = nd.Dropout(a, p=0.5, axes=(1, 2), mode="always").asnumpy()
    # per-sample constant: every kept sample is all-2.0, dropped all-0
    for i in range(4):
        u = onp.unique(out[i])
        assert len(u) == 1, out[i]


def test_slice_partial_infer():
    x = mx.sym.var("x")
    y = mx.sym.slice_axis(x, axis=1, begin=0, end=2)
    _, out, _ = y.infer_shape_partial(x=(4, 0))
    # unknown input dim: partial inference must not crash
    assert out is not None


def test_float16_min_max():
    a = onp.array([1.0, 2.0, -3.0], "float16")
    x = nd.array(a, dtype="float16")
    assert float(nd.max(x).asnumpy()) == 2.0
    assert float(nd.min(x).asnumpy()) == -3.0


# --------------------------------------------------------- introspection

def test_get_all_registered_operators():
    from mxnet_tpu.ops.registry import list_ops
    ops = list_ops()
    assert len(ops) > 400
    for must in ("Convolution", "FullyConnected", "BatchNorm", "Pooling"):
        assert must in ops


def test_get_operator_arguments():
    from mxnet_tpu import _c_api_impl as impl
    name, doc, args, types, descs, kv, ret = \
        impl.atomic_symbol_info("FullyConnected")
    assert name == "FullyConnected"
    assert "data" in args and "weight" in args
    assert len(args) == len(types) == len(descs)


def test_op_output_names_monitor():
    """The executor monitor reports internal op output names (reference
    test_op_output_names_monitor)."""
    x = mx.sym.var("data")
    y = mx.sym.Activation(mx.sym.FullyConnected(
        x, num_hidden=3, name="fc"), act_type="relu", name="act")
    ex = y.simple_bind(mx.cpu(), grad_req="null", data=(2, 4))
    seen = []
    ex.set_monitor_callback(lambda n, arr: seen.append(str(n)), False)
    ex.forward(is_train=False)
    assert any("fc" in n for n in seen), seen
    assert any("act" in n for n in seen), seen
    # monitor_all=False: bound inputs are not reported
    assert "data" not in seen


def test_op_all_names_monitor():
    x = mx.sym.var("data")
    y = mx.sym.FullyConnected(x, num_hidden=3, name="fc")
    ex = y.simple_bind(mx.cpu(), grad_req="null", data=(2, 4))
    seen = []
    ex.set_monitor_callback(lambda n, arr: seen.append(str(n)), True)
    ex.forward(is_train=False)
    assert "data" in seen, seen


def test_context_num_devices():
    assert mx.context.num_gpus() >= 0  # device count query never raises


# ------------------------------------------------------------- regression

def test_regression_outputs():
    """LinearRegressionOutput / MAERegressionOutput / LogisticRegression
    forward values (reference test_regression)."""
    x = _r(4, 3)
    y = _r(4, 3, seed=5)
    lin = nd.LinearRegressionOutput(nd.array(x), nd.array(y))
    onp.testing.assert_allclose(lin.asnumpy(), x, rtol=1e-6)
    mae = nd.MAERegressionOutput(nd.array(x), nd.array(y))
    onp.testing.assert_allclose(mae.asnumpy(), x, rtol=1e-6)
    log = nd.LogisticRegressionOutput(nd.array(x), nd.array(y))
    onp.testing.assert_allclose(log.asnumpy(), 1 / (1 + onp.exp(-x)),
                                rtol=1e-5)


def test_slice_like_different_types():
    a = nd.array(_r(4, 5))
    like = nd.array(onp.zeros((2, 3), "float32"))
    out = nd.slice_like(a, like)
    onp.testing.assert_allclose(out.asnumpy(), a.asnumpy()[:2, :3])


def test_crop_center_offset():
    a = nd.array(_r(1, 1, 6, 6))
    like = nd.array(onp.zeros((1, 1, 4, 4), "float32"))
    out = nd.Crop(a, like, center_crop=True)
    onp.testing.assert_allclose(out.asnumpy(), a.asnumpy()[:, :, 1:5, 1:5])
