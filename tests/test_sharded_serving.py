"""Sharded multi-chip serving (ISSUE-16): the PR 15 serving planner
threaded through the decode/serving stack.

Covers, on the suite's virtual 8-device CPU mesh (conftest forces
``--xla_force_host_platform_device_count=8``):

- the serving planner sharding an MoE model that is provably
  infeasible on one chip (the planner's own feasibility math);
- :class:`ShardedDecodeEngine`: membership churn and chunked prefill
  compile NOTHING after the first fused decode step (misses == 1),
  with the KV arena and expert weights committed per plan;
- sharded ``.mxa``: in-process restart with zero compiles, plus a
  genuine cross-process restart via
  ``tests/dist/sharded_serving_worker.py`` (fresh interpreter, same
  greedy tokens, ``compiles == 0``);
- the mesh-fingerprint regression: a single-chip artifact is never
  silently installed into a sharded lane (typed fallback + counted
  ``cachedop.pcache.fallback`` row);
- ``tools/prewarm.py --check --mesh``: exit 2 on mesh drift;
- :class:`ShardedReplica`: chip-host loss -> re-plan on survivors,
  typed ``PlanError`` when no pool remains;
- gateway composition: a sharded replica scrapes its mesh into the
  replica table, ``/generate`` flows through the gateway, the
  autoscaler counts chips (not replicas), and the Prometheus
  exposition carries the ``mesh`` label.
"""
import importlib.util
import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import aot, nd, pcache
from mxnet_tpu.models.moe_transformer import moe_lm_tiny
from mxnet_tpu.parallel import planner
from mxnet_tpu.serving.generation import GenerationScheduler
from mxnet_tpu.serving.sharded import (ShardedDecodeEngine,
                                       ShardedInferenceEngine,
                                       ShardedReplica, arena_spec)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "dist", "sharded_serving_worker.py")

SLOTS, SEQ, EXPERTS = 8, 32, 8


def _net(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = moe_lm_tiny(n_experts=EXPERTS)
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((1, 8), "int32")))
    return net


def _kv_bytes(net):
    return (2 * net.num_layers * SLOTS * SEQ * net.num_heads *
            net.head_dim * np.dtype("float32").itemsize)


def _drive(eng, steps=3):
    """One slot through prefill + ``steps`` greedy decode steps."""
    slot = eng.cache.acquire()
    tok = eng.prefill(slot, np.arange(1, 9, dtype=np.int32))
    tokens = np.zeros(SLOTS, np.int32)
    temps = np.zeros(SLOTS, np.float32)
    tokens[slot] = tok
    out = [int(tok)]
    for _ in range(steps):
        nxt = eng.decode_step(tokens, temps)
        eng.cache.advance([slot])
        tokens[slot] = nxt[slot]
        out.append(int(nxt[slot]))
    eng.cache.release(slot)
    return out


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """A compiled sharded lane + its exported ``.mxa`` directory,
    shared by the AOT / fingerprint / prewarm / replica / gateway
    tests (one engine build instead of five)."""
    art = str(tmp_path_factory.mktemp("sharded_mxa"))
    eng = ShardedDecodeEngine(_net(), num_slots=SLOTS, max_seq=SEQ,
                              chunk=0, name="t16_shared")
    tokens = _drive(eng)
    header = eng.export_artifacts(art)
    yield {"engine": eng, "dir": art, "header": header,
           "tokens": tokens}
    eng.close()


# ---------------------------------------------------------------------------
# planner math + arena placement (no engine)
# ---------------------------------------------------------------------------

def test_serving_plan_shards_infeasible_moe():
    net = _net()
    profile = net.profile(SLOTS, seq=SEQ)
    kv = _kv_bytes(net)
    single = planner.ShardingPlan()
    need1 = single.serving_memory_per_device(profile, kv_bytes=kv)
    budget = int(max(
        need1 * 0.6,
        planner.min_serving_memory_per_device(8, profile,
                                              kv_bytes=kv) * 1.05))
    # infeasible on one chip by the planner's own math...
    reason = single.serving_feasible(profile, hbm_bytes=budget,
                                     kv_bytes=kv)
    assert reason and "bytes/device" in reason
    # ...and the serving planner shards it over the expert axis
    plan = planner.plan_serving(8, profile, hbm_bytes=budget,
                                kv_bytes=kv)
    assert plan.ep > 1
    assert plan.serving_feasible(profile, hbm_bytes=budget,
                                 kv_bytes=kv) is None
    assert plan.serving_memory_per_device(profile, kv_bytes=kv) <= budget


def test_arena_spec_follows_plan():
    from jax.sharding import PartitionSpec as P
    shape = (4, 8, SEQ, 4, 16)   # (layers, slots, seq, heads, head_dim)
    # expert plan: slots shard over ep; layers stay whole (pp == 1)
    assert arena_spec(planner.ShardingPlan(ep=8), shape) \
        == P(None, ("ep",))
    # pipeline plan: layer axis shards over pp when divisible
    sp = arena_spec(planner.ShardingPlan(pp=2), shape)
    assert sp[0] == "pp" and not sp[1]
    # indivisible slot dim -> slots replicated, not misplaced
    odd = (4, 7, SEQ, 4, 16)
    sp = arena_spec(planner.ShardingPlan(ep=8), odd)
    assert sp[0] is None and not sp[1]


# ---------------------------------------------------------------------------
# the sharded decode lane
# ---------------------------------------------------------------------------

def test_sharded_decode_churn_compiles_once():
    from jax.sharding import PartitionSpec as P
    eng = ShardedDecodeEngine(_net(), num_slots=SLOTS, max_seq=SEQ,
                              chunk=0, name="t16_churn")
    try:
        assert eng.plan.ep == EXPERTS  # expert-parallel serving
        # arena committed on the plan's mesh, slots over the ep axis
        assert eng.cache.arena_sharding.spec == P(None, ("ep",))
        assert (eng.cache.k_arena._data.sharding
                == eng.cache.arena_sharding)
        # expert stacks placed expert-parallel by naming convention
        shardings = eng.param_shardings()
        expert = [s for n, s in shardings.items() if "stack_expert_" in n]
        assert expert and all(s.spec == P("pp", "ep") for s in expert)

        slot = eng.cache.acquire()
        tok = eng.prefill(slot, np.arange(1, 9, dtype=np.int32))
        tokens = np.zeros(SLOTS, np.int32)
        temps = np.zeros(SLOTS, np.float32)
        tokens[slot] = tok
        out = eng.decode_step(tokens, temps)
        eng.cache.advance([slot])
        tokens[slot] = out[slot]
        # membership churn: slots join/leave, chunked prefill runs —
        # the fused decode step never recompiles
        s2 = eng.cache.acquire()
        tokens[s2] = eng.prefill(s2, np.arange(3, 13, dtype=np.int32))
        eng.decode_step(tokens, temps)
        eng.cache.advance([slot, s2])
        eng.cache.release(slot)
        s3 = eng.cache.acquire()
        eng.prefill_chunks(s3, np.arange(2, 20, dtype=np.int32), 0)
        eng.decode_step(tokens, temps)
        eng.cache.advance([s2, s3])
        assert eng.compile_stats()["decode"]["misses"] == 1
        # arena still canonically placed after many functional commits
        assert (eng.cache.k_arena._data.sharding
                == eng.cache.arena_sharding)
    finally:
        eng.close()


def test_aot_restart_zero_compiles_in_process(exported):
    eng2 = ShardedDecodeEngine(_net(), num_slots=SLOTS, max_seq=SEQ,
                               chunk=0, name="t16_restart")
    try:
        loaded = eng2.load_artifacts(exported["dir"])
        assert loaded >= 2   # decode + prefill at least
        toks = _drive(eng2)
        assert toks == exported["tokens"]  # same params, same machine code
        assert sum(v["misses"]
                   for v in eng2.compile_stats().values()) == 0
    finally:
        eng2.close()


def test_single_chip_artifact_refused_by_sharded_lane(exported,
                                                      tmp_path):
    """Regression (the aot.py mesh-fingerprint fix): an artifact
    exported WITHOUT a mesh can never be silently installed into a
    sharded lane — typed fallback, counted, lane unharmed."""
    eng = exported["engine"]
    # the same records, re-stamped as a single-chip export
    header, records = aot.read_artifact(
        os.path.join(exported["dir"], aot.ARTIFACT_NAME))
    single_dir = tmp_path / "single"
    single_dir.mkdir()
    aot.write_artifact(str(single_dir / aot.ARTIFACT_NAME), records,
                       extra=header["extra"], fp=aot.fingerprint())
    before = pcache.stats().get("aot_fallbacks", 0)
    # (the RuntimeWarning fires once per process; the COUNTER is the
    # stable observable — every refusal adds a pcache.fallback row)
    assert eng.load_artifacts(str(single_dir)) == 0
    assert pcache.stats().get("aot_fallbacks", 0) == before + 1
    # and the mismatch is the mesh key specifically, both directions
    sharded_fp = aot.fingerprint(eng.mesh)
    assert not aot.fingerprint_matches(aot.fingerprint(),
                                       current=sharded_fp)
    assert not aot.fingerprint_matches(sharded_fp,
                                       current=aot.fingerprint())
    assert any(d.startswith("mesh:")
               for d in aot.fingerprint_diff(aot.fingerprint(),
                                             current=sharded_fp))


# ---------------------------------------------------------------------------
# prewarm --check: mesh drift gate
# ---------------------------------------------------------------------------

def _prewarm_tool():
    spec = importlib.util.spec_from_file_location(
        "prewarm_tool", os.path.join(REPO, "tools", "prewarm.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_prewarm_check_mesh_drift(exported):
    from mxnet_tpu.serving.fleet import write_manifest
    tool = _prewarm_tool()
    manifest = write_manifest(exported["dir"])
    # the manifest carries the mesh with the artifact (fleet-visible)
    exe = manifest["executables"]
    assert exe["engine"] == "sharded_decode"
    assert exe["mesh"] == exported["header"]["fingerprint"]["mesh"]
    assert exe["plan"]["ep"] == EXPERTS

    # default expectation is a single-chip lane -> sharded artifact is
    # mesh drift, exit 2, with the dedicated status + reason
    code, report = tool.check(exported["dir"])
    assert code == 2 and report["status"] == "mesh-drift"
    assert "mesh drift" in report["error"]
    # the planned mesh as expectation -> gate passes
    code, report = tool.check(exported["dir"],
                              mesh=exe["mesh"])
    assert code == 0 and report["status"] == "ok"
    # operator shorthand omits size-1 axes (the docs' `--mesh dp=1,ep=8`):
    # the lane materializes them at 1, so the gate must still pass
    code, report = tool.check(exported["dir"],
                              mesh=tool._parse_mesh("dp=1,ep=%d" % EXPERTS))
    assert code == 0 and report["status"] == "ok"
    # a shrunken surviving pool's mesh -> drift again (exit 2)
    code, report = tool.check(exported["dir"],
                              mesh={"dp": 1, "pp": 1, "ep": 4,
                                    "tp": 1, "sp": 1})
    assert code == 2 and report["status"] == "mesh-drift"
    # --mesh spec parsing
    assert tool._parse_mesh("dp=1, ep=8") == {"dp": 1, "ep": 8}
    assert tool._parse_mesh("none") is None
    with pytest.raises(SystemExit):
        tool._parse_mesh("ep8")


# ---------------------------------------------------------------------------
# replica: chip-host loss -> re-plan
# ---------------------------------------------------------------------------

def test_replica_replan_on_host_loss(exported):
    rep = ShardedReplica(_net(), artifacts_dir=exported["dir"],
                         engine_kwargs={"num_slots": SLOTS,
                                        "max_seq": SEQ, "chunk": 0},
                         name="t16_replica")
    try:
        assert rep.aot_loaded >= 2        # restart installed machine code
        assert rep.n_devices == 8 and rep.plan.ep == EXPERTS
        before = pcache.stats().get("aot_fallbacks", 0)
        # lose half the pool: re-plan on survivors; the 8-chip artifact
        # must be refused under the 4-chip mesh, not installed
        report = rep.replan(lost=jax.devices()[4:])
        assert report["to"]["n_devices"] == 4
        assert rep.plan.ep == 4 and rep.aot_loaded == 0
        assert rep.mesh_info()["generation"] == 1
        assert pcache.stats().get("aot_fallbacks", 0) == before + 1
        _drive(rep.engine)
        assert rep.compile_stats()["decode"]["misses"] == 1
        # no survivors at all -> the planner's typed error
        with pytest.raises(planner.PlanError):
            rep.replan(devices=[])
    finally:
        rep.close()


# ---------------------------------------------------------------------------
# gateway composition: mesh label, chips-weighted capacity, /generate
# ---------------------------------------------------------------------------

def test_gateway_serves_sharded_replica_with_mesh_label(exported):
    from mxnet_tpu.serving.gateway import Autoscaler, Gateway
    from mxnet_tpu.serving.server import ModelServer
    sched = GenerationScheduler(exported["engine"])
    srv = ModelServer(None, port=0, generator=sched).start()
    gw = Gateway(replicas=[srv.url], scrape_ms=0)
    gw.start()
    try:
        gw.scrape_once()
        rep = gw.replicas()[0]
        # the scrape carried the engine's mesh into the replica table
        assert rep.chips == 8
        assert rep.mesh["n_devices"] == 8
        assert rep.mesh["plan"]["ep"] == EXPERTS
        assert rep.describe()["chips"] == 8
        # autoscaler capacity math counts chips, not replicas
        backend = type("B", (), {"spawn": staticmethod(lambda: None),
                                 "stop": staticmethod(lambda rid: None)})
        sig = Autoscaler(gw, backend=backend, min_replicas=1,
                         max_replicas=2).evaluate()
        assert sig["chips"] == 8 and sig["ready"] == 1
        # live /generate traffic through the gateway, no recompiles
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_new_tokens": 4}).encode()
        raw = urllib.request.urlopen(urllib.request.Request(
            gw.url + "/generate", data=body), timeout=120).read()
        lines = [json.loads(l) for l in raw.splitlines() if l.strip()]
        toks = [l["token"] for l in lines if "token" in l]
        assert len(toks) == 4
        stats = exported["engine"].compile_stats()
        assert stats["decode"]["misses"] == 1
        # Prometheus exposition: per-replica samples carry the mesh size
        with urllib.request.urlopen(gw.url + "/metrics.prom",
                                    timeout=5) as resp:
            text = resp.read().decode()
        assert 'mxtpu_gateway_replica_up{replica="0",mesh="8"} 1' in text
        assert 'mxtpu_gateway_replica_chips{replica="0",mesh="8"} 8' \
            in text
    finally:
        gw.close()
        srv.stop()
        sched.close()


# ---------------------------------------------------------------------------
# cross-process restart (the honest zero-compile claim)
# ---------------------------------------------------------------------------

def _run_worker(scenario, art, out_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # the worker forces its own 8 devices
    env.update(SHARDED_SCENARIO=scenario, SHARDED_DIR=str(art),
               SHARDED_OUT=str(out_path))
    proc = subprocess.run([sys.executable, WORKER], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    with open(out_path) as f:
        return json.load(f)


def test_cross_process_aot_restart(tmp_path):
    art = tmp_path / "mxa"
    art.mkdir()
    exp = _run_worker("export", art, tmp_path / "export.json")
    assert exp["decode_misses"] == 1
    assert exp["fingerprint_mesh"]["ep"] == EXPERTS
    res = _run_worker("restart", art, tmp_path / "restart.json")
    # a genuinely fresh process serves off the .mxa: zero compiles,
    # bit-identical greedy trajectory
    assert res["loaded"] >= 2
    assert res["compiles"] == 0
    assert res["tokens"] == exp["tokens"]
    assert res["plan"] == exp["plan"]


# ---------------------------------------------------------------------------
# the bucketed predict lane on a mesh
# ---------------------------------------------------------------------------

def test_sharded_inference_engine_predict_and_aot(tmp_path):
    from mxnet_tpu import cached_op, gluon

    def _dense():
        mx.random.seed(0)
        np.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"))
        net.add(gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        net(nd.zeros((1, 16)))
        return net

    x = np.random.RandomState(1).standard_normal((8, 16)).astype(
        "float32")
    ref = _dense()(nd.array(x)).asnumpy()

    plan = planner.ShardingPlan(dp=8)
    eng = ShardedInferenceEngine(_dense(), plan=plan, buckets=(8,),
                                 name="t16_pred")
    try:
        got = eng.predict(nd.array(x))
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-5,
                                   atol=2e-5)
        assert eng.mesh_info()["n_devices"] == 8
        eng.export_artifacts(str(tmp_path))
    finally:
        eng.close()

    eng2 = ShardedInferenceEngine(_dense(), plan=plan, buckets=(8,),
                                  name="t16_pred2")
    try:
        assert eng2.load_artifacts(str(tmp_path)) >= 1
        misses0 = cached_op.cache_stats()["misses"]
        got = eng2.predict(nd.array(x))
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-5,
                                   atol=2e-5)
        assert cached_op.cache_stats()["misses"] == misses0
    finally:
        eng2.close()
