"""Multi-process distributed test: launches 2 real processes through
tools/launch.py (local tracker role) running the dist_sync_kvstore
invariants over the jax.distributed CPU backend (reference
tests/nightly/dist_sync_kvstore.py)."""
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


import pytest


@pytest.mark.parametrize("nproc", [2, 4])
def test_dist_sync_kvstore(nproc):
    """The reference dist_sync_kvstore.py invariant checklist
    (init/push/pull ordering, repeated-push rounds, pushpull, multi-key,
    row_sparse pulls, 2bit-compressed push with error feedback, barrier,
    dead-node count) over n real processes."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers use 1 CPU device per process
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")})
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", str(nproc), "--launcher", "local",
           "--coordinator", "127.0.0.1:%d" % port,
           sys.executable,
           os.path.join(REPO, "tests", "dist",
                        "dist_sync_kvstore_worker.py")]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    sys.stderr.write(proc.stdout[-2000:] + proc.stderr[-2000:])
    assert proc.returncode == 0, \
        "distributed workers failed:\n%s\n%s" % (proc.stdout[-3000:],
                                                 proc.stderr[-3000:])
    for r in range(nproc):
        assert "rank %d OK" % r in proc.stdout
