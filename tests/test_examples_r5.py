"""E2E tests for the round-5 example ports (VERDICT r4 item 2): each
drives the example's `train` entry exactly as the CLI does and asserts
the capability the reference example demonstrates — convergence, learned
behavior, or structural properties (sparse updates, eval determinism,
posterior statistics)."""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for sub in ("reinforcement-learning", "neural-style", "fcn-xs", "nce-loss",
            "cnn_text_classification", "named_entity_recognition",
            "multi-task", "bi-lstm-sort", "capsnet", "stochastic-depth",
            "bayesian-methods", "svrg_module", "vae-gan",
            "speech_recognition"):
    sys.path.insert(0, os.path.join(REPO, "example", sub))


def test_rl_dqn_gridworld():
    """DQN learns the optimal gridworld path: replay buffer + target
    network + bootstrapped targets (no dataset labels)."""
    from dqn import train
    _, greedy_return, steps = train(episodes=150, log=lambda *a: None)
    # optimal: 8 moves (-1 each except the final +10) = +3.0
    assert greedy_return >= 2.0, greedy_return
    assert steps <= 12, steps


def test_rl_actor_critic_corridor():
    """Advantage actor-critic improves the policy return."""
    from actor_critic import train
    rets = train(episodes=300, log=lambda *a: None)
    first, last = np.mean(rets[:30]), np.mean(rets[-30:])
    assert last > first, (first, last)
    assert last > 3.0, last


def test_neural_style_optimizes_input():
    """Gradients w.r.t. the INPUT image: loss drops and the image's Gram
    statistics move decisively toward the style target."""
    from nstyle import train
    losses, style_dist, init_dist = train(steps=60, log=lambda *a: None)
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])
    assert style_dist < 0.3 * init_dist, (style_dist, init_dist)


def test_fcn_segmentation():
    """Deconvolution upsample + Crop + per-pixel softmax converge on
    synthetic shapes."""
    from fcn import train
    acc, _ = train(epochs=8, log=lambda *a: None)
    assert acc > 0.9, acc


def test_nce_sparse_embedding_updates():
    """NCE trains against sampled noise; embedding rows outside
    (labels + noise head) keep their initial values — the gradient is
    row-sparse."""
    from toy_nce import train
    losses, init_e, fin_e, touched = train(epochs=8, log=lambda *a: None)
    assert losses[-1] < 0.3 * losses[0]
    untouched = sorted(set(range(len(fin_e))) - touched)
    assert len(untouched) > 50, len(untouched)
    np.testing.assert_array_equal(fin_e[untouched], init_e[untouched])


def test_cnn_text_classification():
    """Kim-CNN separates order-sensitive trigrams a bag-of-words can't."""
    from text_cnn import train
    acc = train(epochs=6, log=lambda *a: None)
    assert acc > 0.9, acc


def test_named_entity_recognition():
    """BiLSTM tagger uses left and right context; padding masked out."""
    from ner import train
    acc, ent_recall = train(epochs=8, log=lambda *a: None)
    assert acc > 0.95, acc
    assert ent_recall > 0.9, ent_recall


def test_multitask_two_heads():
    """Joint loss through a shared trunk trains both heads."""
    from multitask import train
    acc_digit, acc_parity = train(epochs=6, log=lambda *a: None)
    assert acc_digit > 0.9, acc_digit
    assert acc_parity > 0.9, acc_parity


def test_bilstm_sort():
    """Sorting needs global sequence context (the Bi in BiLSTM)."""
    from sort import train
    tok_acc, seq_acc = train(epochs=30, log=lambda *a: None)
    assert tok_acc > 0.75, tok_acc
    assert seq_acc > 0.1, seq_acc


def test_capsnet_routing():
    """Dynamic routing-by-agreement + margin loss converge."""
    from capsnet import train
    acc = train(epochs=5, log=lambda *a: None)
    assert acc > 0.9, acc


def test_stochastic_depth():
    """Random block skipping trains; inference is deterministic with the
    survival-probability scaling."""
    from stochastic_depth import train
    acc, deterministic = train(epochs=6, log=lambda *a: None)
    assert acc > 0.9, acc
    assert deterministic


def test_sgld_posterior():
    """SGLD samples match the closed-form Bayesian posterior mean and
    genuinely spread (sampler, not optimizer)."""
    from sgld import train
    S, mu_post, Sigma = train(steps=3000, log=lambda *a: None)
    assert np.abs(S.mean(0) - mu_post).max() < 0.1
    # spread is within an order of magnitude of the posterior stddev
    post_std = np.sqrt(np.diag(Sigma))
    assert (S.std(0) > 0.3 * post_std).all(), (S.std(0), post_std)


def test_svrg_beats_sgd():
    """Variance reduction reaches a lower loss than SGD at the same lr
    and step budget."""
    from svrg import train
    sgd_loss, svrg_loss = train(epochs=10, log=lambda *a: None)
    assert svrg_loss < 0.8 * sgd_loss, (svrg_loss, sgd_loss)


def test_vaegan():
    """Reparameterized VAE with adversarial feature matching: recon
    improves, KL stays finite, prior samples are in range."""
    from vaegan import train
    hist, samples = train(epochs=8, log=lambda *a: None)
    assert hist[-1][0] < 0.5 * hist[0][0], (hist[0], hist[-1])
    assert 0.0 < hist[-1][1] < 50.0
    assert samples.min() >= 0.0 and samples.max() <= 1.0


def test_speech_ctc():
    """Conv+BiGRU+CTC learns unaligned phoneme sequences."""
    from train_speech import train
    ser = train(epochs=16, log=lambda *a: None)
    assert ser < 0.5, ser
