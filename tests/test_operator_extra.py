"""Oracle + gradient tests for the expanded op corpus (reference
src/operator/tensor, optimizer_op.cc, random/, la_op.cc, image/,
numpy/ registrations; test strategy mirrors
tests/python/unittest/test_operator.py table-driven oracle checks)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd as ag

R = np.random.RandomState(7)


def A(*shape, dtype=np.float32, scale=1.0, pos=False):
    x = R.randn(*shape).astype(dtype) * scale
    return np.abs(x) + 0.5 if pos else x


def check(op_name, np_fn, arrays, rtol=1e-5, atol=1e-6, **kwargs):
    op = getattr(nd, op_name)
    out = op(*[nd.array(a) for a in arrays], **kwargs)
    expect = np_fn(*arrays)
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=rtol, atol=atol,
                               err_msg=op_name)


# ---------------------------------------------------------------- scalars

SCALAR_CASES = [
    ("_equal_scalar", lambda x: (x == 0.5).astype(x.dtype), {"scalar": 0.5}),
    ("_not_equal_scalar", lambda x: (x != 0.5).astype(x.dtype),
     {"scalar": 0.5}),
    ("_greater_scalar", lambda x: (x > 0.1).astype(x.dtype),
     {"scalar": 0.1}),
    ("_greater_equal_scalar", lambda x: (x >= 0.1).astype(x.dtype),
     {"scalar": 0.1}),
    ("_lesser_scalar", lambda x: (x < 0.1).astype(x.dtype), {"scalar": 0.1}),
    ("_lesser_equal_scalar", lambda x: (x <= 0.1).astype(x.dtype),
     {"scalar": 0.1}),
    ("_maximum_scalar", lambda x: np.maximum(x, 0.2), {"scalar": 0.2}),
    ("_minimum_scalar", lambda x: np.minimum(x, 0.2), {"scalar": 0.2}),
    ("_mod_scalar", lambda x: np.mod(x, 1.5), {"scalar": 1.5}),
    ("_rmod_scalar", lambda x: np.mod(np.float32(1.5), x), {"scalar": 1.5}),
    ("_hypot_scalar", lambda x: np.hypot(x, 2.0), {"scalar": 2.0}),
]


@pytest.mark.parametrize("name,fn,kw", SCALAR_CASES,
                         ids=[c[0] for c in SCALAR_CASES])
def test_scalar_ops(name, fn, kw):
    check(name, fn, [A(3, 4)], **kw)


def test_logical_binary():
    x, y = A(4), A(4)
    check("_logical_and",
          lambda a, b: np.logical_and(a, b).astype(a.dtype), [x, y])
    check("_logical_or",
          lambda a, b: np.logical_or(a, b).astype(a.dtype), [x, y])
    check("_logical_xor",
          lambda a, b: np.logical_xor(a, b).astype(a.dtype), [x, y])


def test_camelcase_aliases_resolve():
    for name in ["_PlusScalar", "_MulScalar", "_DivScalar", "_PowerScalar",
                 "_MaximumScalar", "_EqualScalar", "_Hypot", "_Mod",
                 "less", "less_equal"]:
        assert mx.ops.get_op(name) is not None, name


# --------------------------------------------------------------- creation

def test_creation_ops():
    np.testing.assert_allclose(
        nd._arange(stop=10.0, step=2.0).asnumpy(), np.arange(0, 10, 2,
                                                             np.float32))
    np.testing.assert_allclose(
        nd._linspace(0.0, 1.0, num=5).asnumpy(),
        np.linspace(0, 1, 5, dtype=np.float32))
    np.testing.assert_allclose(nd._eye(N=3, k=1).asnumpy(),
                               np.eye(3, k=1, dtype=np.float32))
    np.testing.assert_allclose(nd._full(shape=(2, 2), value=7).asnumpy(),
                               np.full((2, 2), 7, np.float32))
    assert nd._zeros(shape=(3,)).asnumpy().sum() == 0
    assert nd._ones(shape=(3,)).asnumpy().sum() == 3


def test_histogram():
    x = A(100)
    counts, edges = nd._histogram(nd.array(x), bin_cnt=10, range=(-3, 3))
    ec, ee = np.histogram(x, bins=10, range=(-3, 3))
    np.testing.assert_array_equal(counts.asnumpy(), ec)
    np.testing.assert_allclose(edges.asnumpy(), ee, rtol=1e-6, atol=1e-6)


def test_shuffle_is_permutation():
    x = np.arange(32, dtype=np.float32)
    out = nd._shuffle(nd.array(x)).asnumpy()
    np.testing.assert_array_equal(np.sort(out), x)


# --------------------------------------------------------------- indexing

def test_ravel_unravel():
    shape = (4, 5, 6)
    multi = np.stack([R.randint(0, s, 10) for s in shape]).astype(np.float32)
    flat = nd._ravel_multi_index(nd.array(multi), shape=shape)
    expect = np.ravel_multi_index(multi.astype(np.int64), shape)
    np.testing.assert_array_equal(flat.asnumpy().astype(np.int64), expect)
    back = nd._unravel_index(flat, shape=shape)
    np.testing.assert_array_equal(back.asnumpy(), multi)


def test_slice_assign():
    x = np.zeros((4, 4), np.float32)
    rhs = np.ones((2, 2), np.float32)
    out = nd._slice_assign(nd.array(x), nd.array(rhs), begin=(1, 1),
                           end=(3, 3))
    expect = x.copy()
    expect[1:3, 1:3] = rhs
    np.testing.assert_array_equal(out.asnumpy(), expect)
    out2 = nd._slice_assign_scalar(nd.array(x), scalar=5.0, begin=(0, 2),
                                   end=(2, 4))
    expect2 = x.copy()
    expect2[0:2, 2:4] = 5.0
    np.testing.assert_array_equal(out2.asnumpy(), expect2)


def test_scatter_set_nd():
    x = np.zeros((3, 3), np.float32)
    indices = np.array([[0, 2], [1, 0]], np.float32)  # rows: dim coords
    rhs = np.array([9.0, 8.0], np.float32)
    out = nd._scatter_set_nd(nd.array(x), nd.array(rhs), nd.array(indices),
                             shape=(3, 3))
    expect = x.copy()
    expect[0, 1] = 9.0
    expect[2, 0] = 8.0
    np.testing.assert_array_equal(out.asnumpy(), expect)


def test_broadcast_reshape_like():
    x = A(1, 4)
    y = A(3, 4)
    np.testing.assert_array_equal(
        nd.broadcast_like(nd.array(x), nd.array(y)).asnumpy(),
        np.broadcast_to(x, y.shape))
    z = A(12)
    np.testing.assert_array_equal(
        nd.reshape_like(nd.array(z), nd.array(y)).asnumpy(),
        z.reshape(3, 4))


def test_reshape_like_negative_axes():
    """MXNet adds ndim to negative begin/end: -1 is the LAST axis."""
    x = A(2, 3, 4)
    y = A(2, 3, 2, 2)
    out = nd.reshape_like(nd.array(x), nd.array(y), lhs_begin=-1,
                          rhs_begin=-2)
    assert out.shape == (2, 3, 2, 2)
    np.testing.assert_array_equal(out.asnumpy(), x.reshape(2, 3, 2, 2))


def test_image_crop_batched_ranks():
    img5 = A(2, 2, 8, 8, 3)  # (T, N, H, W, C)
    out = nd._image_crop(nd.array(img5), x=1, y=2, width=3, height=4)
    np.testing.assert_array_equal(out.asnumpy(), img5[:, :, 2:6, 1:4, :])


def test_split_v2():
    x = A(4, 6)
    parts = nd._split_v2(nd.array(x), sections=3, axis=1)
    expect = np.split(x, 3, axis=1)
    for p, e in zip(parts, expect):
        np.testing.assert_array_equal(p.asnumpy(), e)
    parts2 = nd._split_v2(nd.array(x), indices=(1, 3), axis=1)
    expect2 = np.split(x, [1, 3], axis=1)
    for p, e in zip(parts2, expect2):
        np.testing.assert_array_equal(p.asnumpy(), e)


def test_add_n_moments_square_sum():
    xs = [A(3, 3) for _ in range(4)]
    np.testing.assert_allclose(
        nd.add_n(*[nd.array(x) for x in xs]).asnumpy(), sum(xs), rtol=1e-6)
    x = A(2, 5)
    m, v = nd.moments(nd.array(x), axes=(1,))
    np.testing.assert_allclose(m.asnumpy(), x.mean(axis=1), rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), x.var(axis=1), rtol=1e-5)
    np.testing.assert_allclose(nd._square_sum(nd.array(x)).asnumpy(),
                               (x ** 2).sum(), rtol=1e-5)


def test_sparse_retain_dense():
    x = A(5, 3)
    idx = np.array([0, 3], np.float32)
    out = nd._sparse_retain(nd.array(x), nd.array(idx)).asnumpy()
    expect = np.zeros_like(x)
    expect[[0, 3]] = x[[0, 3]]
    np.testing.assert_array_equal(out, expect)


def test_all_finite():
    assert nd.all_finite(nd.ones((3,))).asnumpy()[0] == 1
    bad = nd.array(np.array([1.0, np.inf], np.float32))
    assert nd.all_finite(bad).asnumpy()[0] == 0
    assert nd.multi_all_finite(nd.ones((2,)), bad,
                               num_arrays=2).asnumpy()[0] == 0


# ----------------------------------------------------------- optimizer ops

def test_sgd_update_matches_formula():
    w, g = A(5), A(5)
    out = nd.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.01,
                        rescale_grad=0.5)
    expect = w - 0.1 * (0.5 * g + 0.01 * w)
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5)


def test_sgd_mom_update():
    w, g, m = A(5), A(5), A(5)
    new_w, new_m = nd.sgd_mom_update(nd.array(w), nd.array(g), nd.array(m),
                                     lr=0.1, momentum=0.9)
    em = 0.9 * m - 0.1 * g
    np.testing.assert_allclose(new_m.asnumpy(), em, rtol=1e-5)
    np.testing.assert_allclose(new_w.asnumpy(), w + em, rtol=1e-5)


def test_adam_update():
    w, g = A(6), A(6)
    m, v = np.zeros(6, np.float32), np.zeros(6, np.float32)
    new_w, new_m, new_v = nd.adam_update(
        nd.array(w), nd.array(g), nd.array(m), nd.array(v), lr=0.01)
    em = 0.1 * g
    ev = 0.001 * g * g
    np.testing.assert_allclose(new_m.asnumpy(), em, rtol=1e-5)
    np.testing.assert_allclose(new_v.asnumpy(), ev, rtol=1e-4)
    np.testing.assert_allclose(
        new_w.asnumpy(), w - 0.01 * em / (np.sqrt(ev) + 1e-8), rtol=1e-5)


def test_ftrl_update():
    w, g = A(4), A(4)
    z, n = np.zeros(4, np.float32), np.zeros(4, np.float32)
    new_w, new_z, new_n = nd.ftrl_update(
        nd.array(w), nd.array(g), nd.array(z), nd.array(n),
        lr=0.1, lamda1=0.01, beta=1.0)
    en = g * g
    sigma = np.sqrt(en) / 0.1
    ez = g - sigma * w
    np.testing.assert_allclose(new_n.asnumpy(), en, rtol=1e-5)
    np.testing.assert_allclose(new_z.asnumpy(), ez, rtol=1e-4, atol=1e-6)
    expect_w = np.where(np.abs(ez) <= 0.01, 0.0,
                        (np.sign(ez) * 0.01 - ez)
                        / ((1.0 + np.sqrt(en)) / 0.1))
    np.testing.assert_allclose(new_w.asnumpy(), expect_w, rtol=1e-4,
                               atol=1e-6)


def test_mp_sgd_keeps_fp32_master():
    w = A(5).astype(np.float16)
    w32 = w.astype(np.float32)
    g = A(5).astype(np.float16)
    new_w, new_w32 = nd.mp_sgd_update(
        nd.array(w, dtype=np.float16), nd.array(g, dtype=np.float16),
        nd.array(w32), lr=0.1)
    assert new_w.dtype == np.float16
    assert new_w32.dtype == np.float32
    np.testing.assert_allclose(new_w32.asnumpy(),
                               w32 - 0.1 * g.astype(np.float32), rtol=1e-3)


def test_multi_sgd_update():
    ws = [A(3), A(4)]
    gs = [A(3), A(4)]
    outs = nd.multi_sgd_update(nd.array(ws[0]), nd.array(gs[0]),
                               nd.array(ws[1]), nd.array(gs[1]),
                               lrs=(0.1, 0.2), wds=(0.0, 0.0),
                               num_weights=2)
    np.testing.assert_allclose(outs[0].asnumpy(), ws[0] - 0.1 * gs[0],
                               rtol=1e-5)
    np.testing.assert_allclose(outs[1].asnumpy(), ws[1] - 0.2 * gs[1],
                               rtol=1e-5)


def test_signsgd_rmsprop_signum():
    w, g = A(5), A(5)
    out = nd.signsgd_update(nd.array(w), nd.array(g), lr=0.1)
    np.testing.assert_allclose(out.asnumpy(), w - 0.1 * np.sign(g),
                               rtol=1e-6)
    n = np.zeros(5, np.float32)
    new_w, new_n = nd.rmsprop_update(nd.array(w), nd.array(g), nd.array(n),
                                     lr=0.1, gamma1=0.9, epsilon=1e-8)
    en = 0.1 * g * g
    np.testing.assert_allclose(new_n.asnumpy(), en, rtol=1e-4)
    np.testing.assert_allclose(new_w.asnumpy(),
                               w - 0.1 * g / np.sqrt(en + 1e-8), rtol=1e-4)


def test_multi_lars():
    lrs = np.array([0.1, 0.2], np.float32)
    wss = np.array([4.0, 0.0], np.float32)
    gss = np.array([1.0, 1.0], np.float32)
    wds = np.array([0.0, 0.0], np.float32)
    out = nd.multi_lars(nd.array(lrs), nd.array(wss), nd.array(gss),
                        nd.array(wds), eta=0.01, eps=0.0)
    np.testing.assert_allclose(out.asnumpy()[0], 0.1 * 0.01 * 2.0 / 1.0,
                               rtol=1e-5)
    np.testing.assert_allclose(out.asnumpy()[1], 0.2, rtol=1e-5)


# --------------------------------------------------------------- random ops

def test_random_samplers_shapes_and_stats():
    out = nd._random_exponential(lam=2.0, shape=(2000,))
    assert out.shape == (2000,)
    assert abs(float(out.asnumpy().mean()) - 0.5) < 0.1
    out = nd._random_gamma(alpha=3.0, beta=2.0, shape=(2000,))
    assert abs(float(out.asnumpy().mean()) - 6.0) < 0.5
    out = nd._random_poisson(lam=4.0, shape=(2000,))
    assert abs(float(out.asnumpy().mean()) - 4.0) < 0.3
    out = nd._random_randint(low=0, high=10, shape=(500,))
    a = out.asnumpy()
    assert a.min() >= 0 and a.max() < 10
    x = nd.ones((100,))
    like = nd._random_normal_like(x, loc=1.0, scale=0.1)
    assert like.shape == (100,)
    assert abs(float(like.asnumpy().mean()) - 1.0) < 0.1


def test_sample_per_row_params():
    lam = nd.array(np.array([1.0, 10.0], np.float32))
    out = nd._sample_poisson(lam, shape=(1000,))
    assert out.shape == (2, 1000)
    m = out.asnumpy().mean(axis=1)
    assert abs(m[0] - 1.0) < 0.3 and abs(m[1] - 10.0) < 1.0


def test_sample_multinomial():
    p = nd.array(np.array([[0.0, 0.0, 1.0], [1.0, 0.0, 0.0]], np.float32))
    out = nd._sample_multinomial(p, shape=(7,))
    a = out.asnumpy()
    assert a.shape == (2, 7)
    assert (a[0] == 2).all() and (a[1] == 0).all()


# ---------------------------------------------------------------- linalg

def test_linalg_det_inverse_slogdet():
    a = A(3, 3) + 3 * np.eye(3, dtype=np.float32)
    np.testing.assert_allclose(nd.linalg_det(nd.array(a)).asnumpy(),
                               np.linalg.det(a), rtol=1e-4)
    np.testing.assert_allclose(nd.linalg_inverse(nd.array(a)).asnumpy(),
                               np.linalg.inv(a), rtol=1e-4, atol=1e-5)
    sign, logdet = nd.linalg_slogdet(nd.array(a))
    es, el = np.linalg.slogdet(a)
    np.testing.assert_allclose(sign.asnumpy(), es, rtol=1e-5)
    np.testing.assert_allclose(logdet.asnumpy(), el, rtol=1e-4)


def test_linalg_potri_gelqf_syevd():
    a = A(4, 4)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    L = np.linalg.cholesky(spd)
    np.testing.assert_allclose(nd.linalg_potri(nd.array(L)).asnumpy(),
                               np.linalg.inv(spd), rtol=1e-3, atol=1e-4)
    b = A(3, 5)
    Lq, Q = nd.linalg_gelqf(nd.array(b))
    np.testing.assert_allclose((Lq.asnumpy() @ Q.asnumpy()), b, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(Q.asnumpy() @ Q.asnumpy().T, np.eye(3),
                               rtol=1e-4, atol=1e-5)
    sym = (a + a.T) / 2
    U, lam = nd.linalg_syevd(nd.array(sym))
    recon = U.asnumpy().T @ np.diag(lam.asnumpy()) @ U.asnumpy()
    np.testing.assert_allclose(recon, sym, rtol=1e-3, atol=1e-4)


def test_linalg_trmm_maketrian():
    a = A(3, 3)
    b = A(3, 3)
    out = nd.linalg_trmm(nd.array(a), nd.array(b), alpha=2.0)
    np.testing.assert_allclose(out.asnumpy(), 2.0 * np.tril(a) @ b,
                               rtol=1e-5)
    tri = A(6)
    m = nd.linalg_maketrian(nd.array(tri))
    back = nd.linalg_extracttrian(m)
    np.testing.assert_allclose(back.asnumpy(), tri, rtol=1e-6)


# ------------------------------------------------------------- loss layers

def test_linear_regression_output_grad():
    x = nd.array(A(4, 3))
    label = nd.array(A(4, 3))
    x.attach_grad()
    with ag.record():
        out = nd.LinearRegressionOutput(x, label)
    out.backward()
    np.testing.assert_allclose(
        x.grad.asnumpy(), (x.asnumpy() - label.asnumpy()) / 3, rtol=1e-5)
    np.testing.assert_array_equal(out.asnumpy(), x.asnumpy())


def test_logistic_regression_output():
    x = nd.array(A(4, 2))
    label = nd.array((A(4, 2) > 0).astype(np.float32))
    x.attach_grad()
    with ag.record():
        out = nd.LogisticRegressionOutput(x, label)
    out.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(out.asnumpy(), sig, rtol=1e-5)
    np.testing.assert_allclose(x.grad.asnumpy(),
                               (sig - label.asnumpy()) / 2, rtol=1e-4)


def test_roi_pooling():
    data = np.arange(1 * 1 * 4 * 4, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = nd.ROIPooling(nd.array(data), nd.array(rois), pooled_size=(2, 2),
                        spatial_scale=1.0)
    np.testing.assert_array_equal(
        out.asnumpy()[0, 0], np.array([[5, 7], [13, 15]], np.float32))


# ---------------------------------------------------------------- image ops

def test_image_to_tensor_normalize():
    img = R.randint(0, 255, (4, 5, 3)).astype(np.uint8)
    t = nd._image_to_tensor(nd.array(img, dtype=np.uint8))
    assert t.shape == (3, 4, 5)
    np.testing.assert_allclose(t.asnumpy(),
                               img.transpose(2, 0, 1) / 255.0, rtol=1e-6)
    norm = nd._image_normalize(t, mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))
    np.testing.assert_allclose(norm.asnumpy(),
                               (img.transpose(2, 0, 1) / 255.0 - 0.5) / 0.2,
                               rtol=1e-5)


def test_image_crop_resize_flip():
    img = A(8, 8, 3)
    c = nd._image_crop(nd.array(img), x=2, y=1, width=4, height=3)
    np.testing.assert_array_equal(c.asnumpy(), img[1:4, 2:6, :])
    r = nd._image_resize(nd.array(img), size=(4, 4))
    assert r.shape == (4, 4, 3)
    f = nd._image_flip_left_right(nd.array(img))
    np.testing.assert_array_equal(f.asnumpy(), img[:, ::-1, :])
    f2 = nd._image_flip_top_bottom(nd.array(img))
    np.testing.assert_array_equal(f2.asnumpy(), img[::-1, :, :])


# ---------------------------------------------------------------- numpy ops

def test_npi_aliases_resolve():
    for name in ["_npi_add", "_npi_mean", "_npi_concatenate", "_npi_einsum",
                 "_npi_svd", "_npi_normal", "_npi_uniform", "_np_sum",
                 "_np_transpose", "_npx_relu", "_npx_softmax", "_npx_topk",
                 "_npx_fully_connected", "_npi_cholesky", "_npi_unique"]:
        assert mx.ops.get_op(name) is not None, name


def test_einsum_tensordot():
    a, b = A(3, 4), A(4, 5)
    check("einsum", lambda x, y: np.einsum("ij,jk->ik", x, y), [a, b],
          subscripts="ij,jk->ik")
    check("tensordot", lambda x, y: np.tensordot(x, y, axes=([1], [0])),
          [a, b], a_axes_summed=(1,), b_axes_summed=(0,))


def test_numpy_misc_oracle():
    x = A(3, 4)
    check("around", lambda v: np.round(v, 1), [x], decimals=1)
    check("std", lambda v: v.std(), [x], rtol=1e-4)
    check("var", lambda v: v.var(), [x], rtol=1e-4)
    check("diff", lambda v: np.diff(v, axis=-1), [x])
    check("trace", lambda v: np.trace(v), [x])
    check("tril", lambda v: np.tril(v), [x])
    check("moveaxis", lambda v: np.moveaxis(v, 0, 1), [x],
          source=0, destination=1)
    check("rot90", lambda v: np.rot90(v), [x])
    check("copysign", lambda a, b: np.copysign(a, b), [x, A(3, 4)])
    check("arctan2", lambda a, b: np.arctan2(a, b), [x, A(3, 4)])
    check("nan_to_num", np.nan_to_num,
          [np.array([np.nan, np.inf, 1.0], np.float32)])
    check("vstack", lambda a, b: np.vstack([a, b]), [x, A(3, 4)])
    check("column_stack", lambda a, b: np.column_stack([a, b]), [x, A(3, 4)])


def test_unique_nonzero_eager():
    x = np.array([1, 2, 2, 3, 3, 3], np.float32)
    np.testing.assert_array_equal(nd.unique(nd.array(x)).asnumpy(),
                                  [1, 2, 3])
    nz = nd.nonzero(nd.array(np.array([[1, 0], [0, 2]], np.float32)))
    np.testing.assert_array_equal(nz.asnumpy(), [[0, 0], [1, 1]])


def test_svd_reconstruction():
    a = A(4, 3)
    u, s, vh = nd._npi_svd(nd.array(a))
    recon = u.asnumpy() @ np.diag(s.asnumpy()) @ vh.asnumpy()
    np.testing.assert_allclose(recon, a, rtol=1e-4, atol=1e-5)


def test_multinomial_counts():
    p = np.array([0.5, 0.5], np.float32)
    out = nd._npi_multinomial(n=100, pvals=nd.array(p))
    a = out.asnumpy()
    assert a.sum() == 100
    assert a.shape == (2,)


def test_gradient_checks_sample():
    """Finite-difference gradient checks on a sample of new differentiable
    ops (reference test strategy: check_numeric_gradient)."""
    cases = [
        ("around", {"decimals": 0}, False),     # zero-grad a.e.
        ("tril", {}, True),
        ("trace", {}, True),
        ("copysign", None, None),  # handled below
    ]
    x = A(3, 3, scale=0.7)
    for name, kw, _ in cases[:3]:
        xa = nd.array(x)
        xa.attach_grad()
        with ag.record():
            out = getattr(nd, name)(xa, **kw).sum()
        out.backward()
        g = xa.grad.asnumpy()
        eps = 1e-3
        num = np.zeros_like(x)
        for i in range(x.shape[0]):
            for j in range(x.shape[1]):
                xp, xm = x.copy(), x.copy()
                xp[i, j] += eps
                xm[i, j] -= eps
                fp = getattr(nd, name)(nd.array(xp), **kw).asnumpy().sum()
                fm = getattr(nd, name)(nd.array(xm), **kw).asnumpy().sum()
                num[i, j] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(g, num, rtol=1e-2, atol=1e-2,
                                   err_msg=name)
