"""Aux frontend depth tests: metrics, gluon.data, io iterators,
lr schedulers, initializers, recordio — semantics from reference
`tests/python/unittest/{test_metric,test_gluon_data,test_io,test_init}.py`
and `python/mxnet/lr_scheduler.py` docstrings."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


# ------------------------------------------------------------------ metrics

def test_accuracy_and_topk():
    acc = mx.metric.Accuracy()
    pred = mx.nd.array(np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]],
                                "float32"))
    label = mx.nd.array(np.array([1, 0, 0], "float32"))
    acc.update([label], [pred])
    name, val = acc.get()
    assert name == "accuracy" and val == pytest.approx(2.0 / 3.0)

    topk = mx.metric.TopKAccuracy(top_k=2)
    topk.update([label], [pred])
    assert topk.get()[1] == 1.0


def test_mse_mae_rmse_crossentropy_perplexity():
    pred = mx.nd.array(np.array([[0.25, 0.75], [0.6, 0.4]], "float32"))
    label = mx.nd.array(np.array([1, 0], "float32"))
    for cls, ref in [(mx.metric.CrossEntropy, None),
                     (mx.metric.Perplexity, None)]:
        m = cls() if cls is mx.metric.CrossEntropy else cls(ignore_label=None)
        m.update([label], [pred])
        v = m.get()[1]
        assert np.isfinite(v)
    ce = -np.log([0.75, 0.6]).mean()
    m = mx.metric.CrossEntropy()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(ce, rel=1e-5)

    y = mx.nd.array(np.array([1.0, 2.0, 3.0], "float32"))
    yhat = mx.nd.array(np.array([1.5, 2.0, 2.0], "float32"))
    for cls, ref in [(mx.metric.MAE, 0.5), (mx.metric.MSE, 1.25 / 3),
                     (mx.metric.RMSE, np.sqrt(1.25 / 3))]:
        m = cls()
        m.update([y], [yhat])
        assert m.get()[1] == pytest.approx(ref, rel=1e-5)


def test_f1_and_composite_and_custom():
    pred = mx.nd.array(np.array([[0.2, 0.8], [0.9, 0.1], [0.4, 0.6]],
                                "float32"))
    label = mx.nd.array(np.array([1, 0, 0], "float32"))
    f1 = mx.metric.F1()
    f1.update([label], [pred])
    # tp=1 fp=1 fn=0 -> precision .5 recall 1 -> f1 = 2/3
    assert f1.get()[1] == pytest.approx(2.0 / 3.0)

    comp = mx.metric.CompositeEvalMetric()
    comp.add(mx.metric.Accuracy())
    comp.add(mx.metric.F1())
    comp.update([label], [pred])
    names, vals = comp.get()
    assert len(names) == 2 and len(vals) == 2

    cm = mx.metric.CustomMetric(lambda l, p: float(np.mean(l)),
                                name="labelmean")
    cm.update([label], [pred])
    assert cm.get()[1] == pytest.approx(1.0 / 3.0)


def test_metric_create_and_reset():
    m = mx.metric.create("acc")
    pred = mx.nd.array(np.array([[0.1, 0.9]], "float32"))
    m.update([mx.nd.array(np.array([1.0], "float32"))], [pred])
    assert m.get()[1] == 1.0
    m.reset()
    assert np.isnan(m.get()[1]) or m.get()[1] == 0.0


# -------------------------------------------------------------- gluon.data

def test_array_dataset_and_dataloader():
    x = np.arange(20, dtype="float32").reshape(10, 2)
    y = np.arange(10, dtype="float32")
    ds = gluon.data.ArrayDataset(mx.nd.array(x), mx.nd.array(y))
    assert len(ds) == 10
    xi, yi = ds[3]
    assert xi.shape == (2,) and float(np.asarray(yi)) == 3.0

    dl = gluon.data.DataLoader(ds, batch_size=4, last_batch="keep")
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 2)
    assert batches[2][0].shape == (2, 2)

    dl2 = gluon.data.DataLoader(ds, batch_size=4, last_batch="discard",
                                shuffle=True)
    bs = list(dl2)
    assert len(bs) == 2
    seen = np.sort(np.concatenate([b[1].asnumpy() for b in bs]))
    assert len(seen) == 8 and len(np.unique(seen)) == 8


def test_samplers():
    seq = list(gluon.data.SequentialSampler(5))
    assert seq == [0, 1, 2, 3, 4]
    rnd = list(gluon.data.RandomSampler(5))
    assert sorted(rnd) == [0, 1, 2, 3, 4]
    bs = list(gluon.data.BatchSampler(gluon.data.SequentialSampler(5), 2,
                                      "keep"))
    assert bs == [[0, 1], [2, 3], [4]]


def test_transforms_compose():
    from mxnet_tpu.gluon.data.vision import transforms as T
    img = mx.nd.array((np.random.RandomState(0).rand(8, 8, 3) * 255)
                      .astype("float32"))
    pipe = T.Compose([T.ToTensor(),
                      T.Normalize(mean=(0.5, 0.5, 0.5),
                                  std=(0.25, 0.25, 0.25))])
    out = pipe(img)
    assert out.shape == (3, 8, 8)
    raw = img.asnumpy().transpose(2, 0, 1) / 255.0
    np.testing.assert_allclose(out.asnumpy(), (raw - 0.5) / 0.25,
                               atol=1e-5)
    cc = T.CenterCrop(4)(img)
    assert cc.shape[:2] == (4, 4)
    rs = T.Resize(16)(img)
    assert rs.shape[:2] == (16, 16)


# ------------------------------------------------------------------ io

def test_ndarray_iter_pad_and_reset():
    x = np.arange(10, dtype="float32").reshape(5, 2)
    y = np.arange(5, dtype="float32")
    it = mx.io.NDArrayIter(x, y, batch_size=2, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 2)
    assert batches[2].pad == 1  # padded final batch
    it.reset()
    again = list(it)
    assert len(again) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(),
                               again[0].data[0].asnumpy())


def test_ndarray_iter_provide_data_label():
    it = mx.io.NDArrayIter(np.zeros((4, 3), "float32"),
                           np.zeros((4,), "float32"), batch_size=2)
    (dname, dshape) = it.provide_data[0][:2]
    (lname, lshape) = it.provide_label[0][:2]
    assert dname == "data" and tuple(dshape) == (2, 3)
    assert lname == "softmax_label" and tuple(lshape) == (2,)


# ------------------------------------------------------------ lr schedulers

def test_lr_schedulers():
    fs = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5,
                                         base_lr=1.0)
    # reference FactorScheduler reduces when num_update > step
    assert fs(0) == 1.0 and fs(10) == 1.0
    assert fs(11) == pytest.approx(0.5)
    assert fs(21) == pytest.approx(0.25)

    mf = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1,
                                              base_lr=1.0)
    assert mf(4) == 1.0
    assert mf(6) == pytest.approx(0.1)
    assert mf(16) == pytest.approx(0.01)

    ps = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert ps(0) == pytest.approx(1.0)
    assert ps(50) == pytest.approx(0.5, abs=0.02)

    cs = mx.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0,
                                         final_lr=0.0)
    assert cs(0) == pytest.approx(1.0)
    assert cs(100) == pytest.approx(0.0, abs=1e-6)


# ------------------------------------------------------------ initializers

def test_initializer_zoo():
    shapes = {}
    for init, check in [
        (mx.init.Zero(), lambda a: (a == 0).all()),
        (mx.init.One(), lambda a: (a == 1).all()),
        (mx.init.Constant(3.0), lambda a: (a == 3.0).all()),
        (mx.init.Uniform(0.1), lambda a: (np.abs(a) <= 0.1).all()),
        (mx.init.Normal(0.01), lambda a: np.abs(a).std() < 0.05),
        (mx.init.Xavier(), lambda a: np.isfinite(a).all()),
        (mx.init.MSRAPrelu(), lambda a: np.isfinite(a).all()),
    ]:
        arr = mx.nd.zeros((8, 16))
        init(mx.init.InitDesc("test_weight"), arr)
        assert check(arr.asnumpy()), type(init).__name__

    # orthogonal: W @ W.T == scale^2 * I for square (default scale 1.414)
    arr = mx.nd.zeros((16, 16))
    mx.init.Orthogonal(scale=1.0)(mx.init.InitDesc("w"), arr)
    a = arr.asnumpy()
    np.testing.assert_allclose(a @ a.T, np.eye(16), atol=1e-3)

    # LSTMBias sets forget-gate biases to 1
    arr = mx.nd.zeros((32,))  # 4 gates x 8 hidden
    mx.init.LSTMBias(forget_bias=1.0)(mx.init.InitDesc("lstm_bias"), arr)
    b = arr.asnumpy()
    assert (b[8:16] == 1.0).all() and b.sum() == 8.0


# --------------------------------------------------------------- recordio

def test_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"world" * 100, b""]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = [r.read() for _ in payloads]
    assert got == payloads
    r.close()


def test_indexed_recordio_and_pack(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(5):
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(header, b"payload%d" % i))
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    h, payload = recordio.unpack(r.read_idx(3))
    assert h.label == 3.0 and payload == b"payload3"
    r.close()
