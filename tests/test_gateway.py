"""Horizontal-serving gateway tests (ISSUE 11).

Coverage: least-loaded routing under skewed load, health-gated
admission, request-id/model-version propagation across failover, sticky
``/generate`` streams (pin + mid-stream replica loss → in-band error),
per-replica breaker ejection/readmission, abrupt replica loss under
``/predict`` load with ZERO client-visible errors, drain-aware rolling
restart with zero drops, the SLO-burn autoscaler on fake ticks, the
``/drain`` + SIGTERM satellites, and the queue-depth gauge satellite.

Two replica flavors:

- **stub replicas** — a pure-stdlib fake of ``ModelServer``'s HTTP
  surface with deterministic health/load/latency and scriptable death
  (the gateway only ever sees HTTP, so routing/failover/stream logic is
  fully exercisable without XLA);
- **real replicas** — in-process :class:`ModelServer` instances for the
  end-to-end paths (correctness of proxied predictions, drain
  semantics, rolling restart).
"""
import http.client
import json
import signal
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.retry import RetryPolicy
from mxnet_tpu.serving import (Autoscaler, Gateway, GatewayMetrics,
                               ModelServer, ServingMetrics)

D_IN, D_OUT = 8, 3
_W = np.linspace(-1, 1, D_IN * D_OUT).reshape(D_IN, D_OUT).astype("float32")


def _linear(x):
    return nd.dot(x, nd.array(_W))


def _ref(x):
    return np.asarray(x, "float32") @ _W


@pytest.fixture(autouse=True)
def _disarm_chaos():
    chaos.clear()
    yield
    chaos.clear()


# ---------------------------------------------------------------------------
# stub replica: ModelServer's HTTP surface, scripted
# ---------------------------------------------------------------------------

class StubReplica:
    """Controllable fake backend. Mutate the public attributes at any
    time; every handler reads them live."""

    def __init__(self, name="stub", health="ok", queue_depth=0,
                 predict_status=200, predict_close=False, delay_s=0.0,
                 model_version=None, gen_tokens=3, gen_delay_s=0.0,
                 gen_die_after=None, gen_status=200):
        self.name = name
        self.health = health
        self.queue_depth = queue_depth
        self.predict_status = predict_status
        self.predict_close = predict_close   # abrupt socket close
        self.delay_s = delay_s
        self.model_version = model_version
        self.gen_tokens = gen_tokens
        self.gen_delay_s = gen_delay_s
        self.gen_die_after = gen_die_after   # close mid-stream after N
        self.gen_status = gen_status
        self.predict_calls = 0
        self.generate_calls = 0
        self.seen_request_ids = []
        self.drained = False
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    self._send(200, {"status": stub.health})
                elif path == "/metrics":
                    self._send(200, {"queue_depth": stub.queue_depth})
                elif path == "/drain":
                    stub.drained = True
                    stub.health = "draining"
                    self._send(202, {"status": "draining"})
                else:
                    self._send(404, {"error": "nope"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self.rfile.read(length)
                rid = self.headers.get("X-Request-Id")
                stub.seen_request_ids.append(rid)
                if self.path.startswith("/generate"):
                    self._generate(rid)
                    return
                stub.predict_calls += 1
                if stub.delay_s:
                    time.sleep(stub.delay_s)
                if stub.predict_close:
                    # replica vanishes mid-request: reset, no reply
                    self.connection.close()
                    self.close_connection = True
                    return
                headers = {}
                if rid:
                    headers["X-Request-Id"] = rid
                if stub.model_version:
                    headers["X-Model-Version"] = stub.model_version
                code = stub.predict_status
                if code != 200:
                    self._send(code, {"error": "scripted %d" % code},
                               headers=headers)
                else:
                    self._send(200, {"output": [1.0], "replica": stub.name},
                               headers=headers)

            def _chunk(self, obj):
                data = (json.dumps(obj) + "\n").encode()
                self.wfile.write(b"%x\r\n" % len(data))
                self.wfile.write(data)
                self.wfile.write(b"\r\n")
                self.wfile.flush()

            def _generate(self, rid):
                stub.generate_calls += 1
                if stub.gen_status != 200:
                    self._send(stub.gen_status,
                               {"error": "scripted %d" % stub.gen_status})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                if rid:
                    self.send_header("X-Request-Id", rid)
                if stub.model_version:
                    self.send_header("X-Model-Version",
                                     stub.model_version)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for i in range(stub.gen_tokens):
                    if stub.gen_die_after is not None \
                            and i >= stub.gen_die_after:
                        # replica dies mid-stream: abrupt close, no
                        # terminal chunk
                        self.connection.close()
                        self.close_connection = True
                        return
                    if stub.gen_delay_s:
                        time.sleep(stub.gen_delay_s)
                    self._chunk({"token": 100 + i, "index": i,
                                 "replica": stub.name})
                self._chunk({"done": True, "n_tokens": stub.gen_tokens,
                             "reason": "length"})
                self.wfile.write(b"0\r\n\r\n")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="stub-replica-%s" % name)
        self._thread.start()

    @property
    def url(self):
        return "http://%s:%d" % self._httpd.server_address[:2]

    def kill(self):
        """Abrupt full death: listener gone, no more replies."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(5.0)

    close = kill


def _fast_retry(**kw):
    """A no-sleep failover policy so tests never wait on backoff."""
    kw.setdefault("max_attempts", 4)
    kw.setdefault("base_delay_ms", 0.0)
    kw.setdefault("jitter", 0.0)
    return RetryPolicy(name="retry.gateway.test", register=False,
                       sleep=lambda s: None, **kw)


def _mk_gateway(stubs, **kw):
    kw.setdefault("scrape_ms", 0)  # tests drive scrape_once() by hand
    kw.setdefault("retry_policy", _fast_retry())
    gw = Gateway(replicas=[s.url for s in stubs], **kw)
    gw.start()
    return gw


def _post(url, payload, rid=None, timeout=10):
    data = json.dumps(payload).encode()
    headers = {"Content-Type": "application/json"}
    if rid:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _get(url, timeout=5, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _stream(url, payload, rid=None, timeout=10):
    """POST /generate and collect the NDJSON lines."""
    import urllib.parse
    u = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
    body = json.dumps(payload).encode()
    headers = {"Content-Type": "application/json",
               "Content-Length": str(len(body))}
    if rid:
        headers["X-Request-Id"] = rid
    conn.request("POST", "/generate", body=body, headers=headers)
    resp = conn.getresponse()
    lines = []
    if resp.status == 200:
        while True:
            line = resp.readline()
            if not line:
                break
            lines.append(json.loads(line))
            if lines[-1].get("done") or lines[-1].get("error"):
                break
    else:
        lines.append(json.loads(resp.read()))
    status, hdrs = resp.status, dict(resp.headers)
    conn.close()
    return status, hdrs, lines


def _wait_unpinned(gw, timeout_s=5.0):
    """The client sees the done/error line strictly before the gateway
    thread can run its unpin, so pin release is an asynchronous
    postcondition — wait for it (bounded) instead of sampling the race."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if all(r.pins == 0 for r in gw.replicas()):
            return True
        time.sleep(0.005)
    return False


# ---------------------------------------------------------------------------
# admission, scraping, routing
# ---------------------------------------------------------------------------

def test_health_gated_admission_and_scrape():
    a = StubReplica("a")
    b = StubReplica("b", health="degraded", queue_depth=5)
    gw = _mk_gateway([a, b])
    try:
        table = gw.replica_table()
        # a: joining -> up on its first healthy scrape (start() scraped);
        # b: degraded never promotes out of joining
        sa = [r for r in table.values() if r["url"] == a.url][0]
        sb = [r for r in table.values() if r["url"] == b.url][0]
        assert sa["state"] == "up" and sa["health"] == "ok"
        assert sb["state"] == "joining" and sb["health"] == "degraded"
        assert sb["queue_depth"] == 5
        b.health = "ok"
        gw.scrape_once()
        sb = [r for r in gw.replica_table().values()
              if r["url"] == b.url][0]
        assert sb["state"] == "up"
        # full death is visible as health=down after a scrape
        b.kill()
        gw.scrape_once()
        sb = [r for r in gw.replica_table().values()
              if r["url"] == b.url][0]
        assert sb["health"] == "down"
        assert any(e["event"] == "replica_down" for e in gw.events())
    finally:
        gw.close()
        a.kill()


def test_least_loaded_routing_skews_away_from_backlog():
    a = StubReplica("a", queue_depth=10)
    b = StubReplica("b", queue_depth=0)
    gw = _mk_gateway([a, b])
    try:
        for _ in range(8):
            status, _, body = _post(gw.url + "/predict", {"data": [1.0]})
            assert status == 200 and body["replica"] == "b"
        assert b.predict_calls == 8 and a.predict_calls == 0
        # load flips: the routing follows the scraped signal
        a.queue_depth, b.queue_depth = 0, 10
        gw.scrape_once()
        for _ in range(4):
            _, _, body = _post(gw.url + "/predict", {"data": [1.0]})
            assert body["replica"] == "a"
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_equal_load_spreads_over_replicas():
    a = StubReplica("a")
    b = StubReplica("b")
    gw = _mk_gateway([a, b])
    try:
        for _ in range(10):
            _post(gw.url + "/predict", {"data": [1.0]})
        # routed-count tiebreak alternates instead of hammering one host
        assert a.predict_calls == 5 and b.predict_calls == 5
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_draining_replica_takes_no_new_requests():
    a = StubReplica("a")
    b = StubReplica("b")
    gw = _mk_gateway([a, b])
    try:
        rid_a = [r for r in gw.replicas() if r.url == a.url][0].id
        gw.mark_draining(rid_a)
        assert a.drained  # gateway told the replica itself via /drain
        for _ in range(6):
            _, _, body = _post(gw.url + "/predict", {"data": [1.0]})
            assert body["replica"] == "b"
        assert a.predict_calls == 0
    finally:
        gw.close()
        a.kill()
        b.kill()


# ---------------------------------------------------------------------------
# failover + propagation
# ---------------------------------------------------------------------------

def test_request_id_survives_failover_retry():
    """Satellite regression: a client-supplied X-Request-Id rides the
    failover retry — the replica that finally serves it and the reply
    both carry the original id (trace stitching key)."""
    a = StubReplica("a", predict_close=True)   # dies on every request
    b = StubReplica("b")
    gw = _mk_gateway([a, b])
    try:
        # force a to be tried first (lower load)
        b.queue_depth = 3
        gw.scrape_once()
        status, headers, body = _post(gw.url + "/predict",
                                      {"data": [1.0]}, rid="rid-e2e-42")
        assert status == 200 and body["replica"] == "b"
        assert headers["X-Request-Id"] == "rid-e2e-42"
        assert "rid-e2e-42" in a.seen_request_ids   # first attempt
        assert "rid-e2e-42" in b.seen_request_ids   # failover attempt
        assert gw.metrics.snapshot()["failovers"] >= 1
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_model_version_header_echoed_end_to_end():
    a = StubReplica("a", model_version="bert=v7")
    gw = _mk_gateway([a])
    try:
        _, headers, _ = _post(gw.url + "/predict", {"data": [1.0]})
        assert headers["X-Model-Version"] == "bert=v7"
    finally:
        gw.close()
        a.kill()


def test_4xx_passes_through_without_failover():
    a = StubReplica("a", predict_status=400)
    b = StubReplica("b")
    gw = _mk_gateway([a, b])
    try:
        b.queue_depth = 3
        gw.scrape_once()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(gw.url + "/predict", {"data": [1.0]})
        assert ei.value.code == 400
        snap = gw.metrics.snapshot()
        assert snap["failovers"] == 0
        assert b.predict_calls == 0  # client errors are not replica faults
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_5xx_fails_over_and_ejects_flapping_replica():
    a = StubReplica("a", predict_status=500)
    b = StubReplica("b")
    gw = _mk_gateway([a, b], eject_failures=3)
    try:
        b.queue_depth = 3
        gw.scrape_once()
        for _ in range(6):
            status, _, body = _post(gw.url + "/predict", {"data": [1.0]})
            assert status == 200 and body["replica"] == "b"
        # a burned its 3 breaker failures, then stopped being offered
        assert a.predict_calls == 3
        snap = gw.metrics.snapshot()
        assert snap["ejections"] == 1 and snap["failovers"] >= 3
        assert any(e["event"] == "replica_ejected" for e in gw.events())
        table = gw.replica_table()
        assert [r for r in table.values()
                if r["url"] == a.url][0]["breaker"] == "open"
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_ejected_replica_readmitted_via_half_open_probe():
    t = [1000.0]
    a = StubReplica("a", predict_status=500)
    b = StubReplica("b")
    gw = _mk_gateway([a, b], eject_failures=2, eject_recovery_ms=5000.0,
                     clock=lambda: t[0])
    try:
        b.queue_depth = 3
        gw.scrape_once()
        for _ in range(3):
            _post(gw.url + "/predict", {"data": [1.0]})
        assert a.predict_calls == 2  # ejected after 2 failures
        a.predict_status = 200       # replica healed
        for _ in range(3):           # still inside recovery window
            _, _, body = _post(gw.url + "/predict", {"data": [1.0]})
            assert body["replica"] == "b"
        assert a.predict_calls == 2
        t[0] += 6.0                  # recovery elapses -> half-open probe
        _post(gw.url + "/predict", {"data": [1.0]})
        assert a.predict_calls == 3  # the probe went to a
        snap = gw.metrics.snapshot()
        assert snap["readmissions"] == 1
        assert any(e["event"] == "replica_readmitted"
                   for e in gw.events())
        table = gw.replica_table()
        assert [r for r in table.values()
                if r["url"] == a.url][0]["breaker"] == "closed"
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_eject_failures_zero_disables_ejection():
    """Knob contract: MXNET_GATEWAY_EJECT_FAILURES<=0 disables ejection
    — a flapping replica keeps being offered (and failed over), its
    breaker never opens."""
    a = StubReplica("a", predict_status=500)
    b = StubReplica("b")
    gw = _mk_gateway([a, b], eject_failures=0)
    try:
        b.queue_depth = 3            # a is preferred every time
        gw.scrape_once()
        for _ in range(8):
            status, _, body = _post(gw.url + "/predict", {"data": [1.0]})
            assert status == 200 and body["replica"] == "b"
        assert a.predict_calls == 8  # never ejected, always retried
        snap = gw.metrics.snapshot()
        assert snap["ejections"] == 0
        table = gw.replica_table()
        assert [r for r in table.values()
                if r["url"] == a.url][0]["breaker"] == "closed"
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_retry_policy_false_single_attempt_typed_503():
    """retry_policy=False (failover disabled): a replica fault still
    surfaces as a typed 503, never a dropped connection."""
    a = StubReplica("a", predict_status=500)
    gw = _mk_gateway([a], retry_policy=False)
    try:
        gw.scrape_once()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(gw.url + "/predict", {"data": [1.0]})
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
        assert a.predict_calls == 1  # single attempt, no retry
    finally:
        gw.close()
        a.kill()


def test_no_routable_replica_returns_503():
    a = StubReplica("a", health="degraded")  # never admitted
    gw = _mk_gateway([a])
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(gw.url + "/predict", {"data": [1.0]})
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
        assert gw.metrics.snapshot()["no_replica"] >= 1
    finally:
        gw.close()
        a.kill()


@pytest.mark.chaos
def test_gateway_forward_chaos_point_absorbed_by_retry():
    a = StubReplica("a")
    gw = _mk_gateway([a])
    try:
        chaos.arm("gateway.forward", "transient", first=1)
        status, _, body = _post(gw.url + "/predict", {"data": [1.0]})
        assert status == 200 and body["replica"] == "a"
        assert chaos.stats()["gateway.forward"]["fires"] == 1
    finally:
        gw.close()
        a.kill()


# ---------------------------------------------------------------------------
# replica loss under load: the zero-client-errors contract
# ---------------------------------------------------------------------------

def _hard_kill(srv):
    """Kill a real in-process ModelServer the way a lost host dies: the
    listener vanishes and queued work is dropped, no drain, no 503s
    sent on purpose."""
    srv._httpd.shutdown()
    srv._httpd.server_close()
    srv.batcher.close(drain=False)


def test_replica_loss_under_predict_load_zero_client_errors():
    """ISSUE acceptance: losing a replica while the gateway serves
    concurrent /predict traffic costs ZERO client-visible errors —
    every request either lands on the dead replica and is rerouted, or
    never sees it."""
    r1 = ModelServer(_linear, port=0, buckets=(1, 2, 4),
                     max_latency_ms=1.0).start()
    r2 = ModelServer(_linear, port=0, buckets=(1, 2, 4),
                     max_latency_ms=1.0).start()
    gw = _mk_gateway([], retry_policy=_fast_retry(max_attempts=6))
    gw.add_replica(r1.url)
    gw.add_replica(r2.url)
    gw.scrape_once()
    errors, oks = [], [0]
    stop = threading.Event()
    x = np.random.randn(D_IN).astype("float32")
    expected = _ref(x[None])[0]

    def client():
        while not stop.is_set():
            try:
                status, _, body = _post(gw.url + "/predict",
                                        {"data": x.tolist()})
                assert status == 200
                np.testing.assert_allclose(body["output"], expected,
                                           rtol=1e-4, atol=1e-5)
                oks[0] += 1
            except Exception as e:  # noqa: BLE001 — counted, not raised
                errors.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)
        _hard_kill(r1)            # replica loss under load
        time.sleep(0.7)
    finally:
        stop.set()
        for t in threads:
            t.join(10.0)
    assert not errors, errors[:5]
    assert oks[0] > 10   # CPU oracle: enough traffic to span the loss
    assert gw.metrics.snapshot()["failovers"] >= 1
    gw.close()
    r2.stop()


# ---------------------------------------------------------------------------
# sticky /generate streams
# ---------------------------------------------------------------------------

def test_generate_stream_sticky_and_relayed():
    a = StubReplica("a", gen_tokens=4, gen_delay_s=0.02)
    b = StubReplica("b", gen_tokens=4, gen_delay_s=0.02)
    gw = _mk_gateway([a, b])
    try:
        results = {}

        def run(key):
            results[key] = _stream(gw.url, {"prompt": [1, 2]},
                                   rid="st-%s" % key)

        t1 = threading.Thread(target=run, args=("one",))
        t2 = threading.Thread(target=run, args=("two",))
        t1.start()
        # wait until stream one's pin is visible, so stream two's pick
        # deterministically sees the pin-loaded replica
        deadline = time.time() + 5.0
        while time.time() < deadline \
                and not any(r.pins for r in gw.replicas()):
            time.sleep(0.005)
        t2.start()
        t1.join(10.0)
        t2.join(10.0)
        for key in ("one", "two"):
            status, _, lines = results[key]
            assert status == 200
            assert lines[-1].get("done") is True
            # sticky: every token line of one stream names ONE replica
            replicas = {l["replica"] for l in lines if "token" in l}
            assert len(replicas) == 1
        # pin-aware load spread: concurrent streams took different
        # replicas (stream two saw stream one's pin)
        assert a.generate_calls == 1 and b.generate_calls == 1
        # pins released after completion
        assert _wait_unpinned(gw)
        assert gw.metrics.snapshot()["streams"] == 2
    finally:
        gw.close()
        a.kill()
        b.kill()


def test_generate_replica_death_mid_stream_in_band_error():
    a = StubReplica("a", gen_tokens=6, gen_die_after=2,
                    gen_delay_s=0.01)
    gw = _mk_gateway([a])
    try:
        status, _, lines = _stream(gw.url, {"prompt": [1]})
        assert status == 200  # stream had committed to 200 already
        tokens = [l for l in lines if "token" in l]
        assert len(tokens) == 2
        assert "error" in lines[-1]
        assert "lost mid-stream" in lines[-1]["error"]
        snap = gw.metrics.snapshot()
        assert snap["stream_errors"] == 1
        assert _wait_unpinned(gw)  # pin released
        assert any(e["event"] == "stream_replica_lost"
                   for e in gw.events())
    finally:
        gw.close()
        a.kill()


def test_generate_pre_stream_failure_fails_over():
    a = StubReplica("a", gen_status=500)
    b = StubReplica("b", gen_tokens=3)
    gw = _mk_gateway([a, b])
    try:
        b.queue_depth = 3
        gw.scrape_once()
        status, headers, lines = _stream(gw.url, {"prompt": [1]},
                                         rid="gen-rid-1")
        assert status == 200
        assert headers["X-Request-Id"] == "gen-rid-1"
        assert lines[-1].get("done") is True
        assert {l["replica"] for l in lines if "token" in l} == {"b"}
        assert gw.metrics.snapshot()["failovers"] >= 1
    finally:
        gw.close()
        a.kill()
        b.kill()


# ---------------------------------------------------------------------------
# drain-aware rolling restart
# ---------------------------------------------------------------------------

class ThreadBackend:
    """In-process backend: replicas are ModelServer instances. restart()
    gracefully stops the old server and brings up a fresh one (new
    ephemeral port, like a respawned process would get)."""

    def __init__(self, model=_linear, **server_kw):
        self.model = model
        self.server_kw = dict(buckets=(1, 2, 4), max_latency_ms=1.0)
        self.server_kw.update(server_kw)
        self.servers = {}
        self.spawned = 0
        self.stopped = 0

    def spawn(self):
        srv = ModelServer(self.model, port=0, **self.server_kw).start()
        self.spawned += 1
        self.servers[srv.url] = srv
        return srv.url, {"server": srv}

    def restart(self, replica):
        old = (replica.meta or {}).get("server")
        if old is not None:
            old.stop(drain=True, timeout=5.0)
            self.servers.pop(old.url, None)
        url, meta = self.spawn()
        replica.meta = meta
        return url

    def stop(self, replica):
        srv = (replica.meta or {}).get("server")
        if srv is not None:
            srv.stop(drain=True, timeout=5.0)
            self.servers.pop(srv.url, None)
            self.stopped += 1

    def close(self):
        for srv in list(self.servers.values()):
            srv.stop(drain=False)
        self.servers.clear()


def test_rolling_restart_zero_dropped_requests():
    """ISSUE acceptance: a full rolling restart of every replica under
    concurrent load completes with zero dropped requests."""
    backend = ThreadBackend()
    gw = _mk_gateway([], backend=backend,
                     retry_policy=_fast_retry(max_attempts=6))
    for _ in range(2):
        url, meta = backend.spawn()
        gw.add_replica(url, meta=meta)
    gw.scrape_once()
    errors, oks = [], [0]
    stop = threading.Event()
    x = np.random.randn(D_IN).astype("float32")

    def client():
        while not stop.is_set():
            try:
                status, _, _ = _post(gw.url + "/predict",
                                     {"data": x.tolist()})
                assert status == 200
                oks[0] += 1
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.2)
        report = gw.rolling_restart(backend, ready_timeout_s=30.0)
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(10.0)
    try:
        assert not errors, errors[:5]
        assert oks[0] > 10   # CPU oracle: enough traffic to span the restart
        assert len(report) == 2 and all(r["ok"] for r in report)
        assert all(r["drained"] for r in report)
        # both replicas really were replaced and readmitted
        assert backend.spawned == 4
        table = gw.replica_table()
        assert all(r["state"] == "up" and r["health"] == "ok"
                   and r["generation"] == 1 for r in table.values())
        kinds = [e["event"] for e in gw.events()]
        assert kinds.count("replica_draining") == 2
        assert kinds.count("replica_readmitted") == 2
        assert "rolling_restart_done" in kinds
        assert gw.metrics.snapshot()["rolling_restarts"] == 1
    finally:
        gw.close()
        backend.close()


def test_rolling_restart_waits_for_inflight_drain():
    """The drain step holds the restart until in-flight work on the
    draining replica finishes — a slow request outlives its replica's
    restart trigger without being dropped."""
    import mxnet_tpu.serving.gateway as gwmod

    def slow(x):
        time.sleep(0.25)
        return _linear(x)

    backend = ThreadBackend(model=slow)
    gw = _mk_gateway([], backend=backend)
    url, meta = backend.spawn()
    rep = gw.add_replica(url, meta=meta)
    gw.scrape_once()
    assert rep.state == gwmod.UP
    result = {}

    def one_request():
        x = np.random.randn(D_IN).astype("float32")
        result["resp"] = _post(gw.url + "/predict", {"data": x.tolist()})

    t = threading.Thread(target=one_request)
    t.start()
    time.sleep(0.08)          # request is in flight on the replica
    report = gw.rolling_restart(backend, ready_timeout_s=30.0)
    t.join(10.0)
    try:
        assert report[0]["ok"] and report[0]["drained"]
        assert result["resp"][0] == 200   # in-flight request completed
    finally:
        gw.close()
        backend.close()


# ---------------------------------------------------------------------------
# autoscaler (fake ticks: no sleeping, no background thread)
# ---------------------------------------------------------------------------

def test_rolling_restart_failed_respawn_not_stuck_draining():
    """A backend.restart() failure must not park the replica in
    DRAINING (which routing AND a supervisor's crash watch skip
    forever): it goes back to JOINING so a respawn/recovery can
    health-gate it up again."""
    class FailingBackend(ThreadBackend):
        def restart(self, replica):
            self.stop(replica)          # old process already gone...
            raise OSError("spawn refused")  # ...and the respawn fails

    backend = FailingBackend()
    gw = _mk_gateway([], backend=backend)
    try:
        url, meta = backend.spawn()
        gw.add_replica(url, meta=meta)
        gw.scrape_once()
        report = gw.rolling_restart(backend, ready_timeout_s=5.0)
        assert len(report) == 1 and report[0]["ok"] is False
        table = gw.replica_table()
        assert all(r["state"] == "joining" for r in table.values())
        assert any(e["event"] == "restart_failed" for e in gw.events())
    finally:
        gw.close()
        backend.close()


class StubBackend:
    """Autoscaler backend over stub replicas."""

    def __init__(self):
        self.stubs = []
        self.stopped = []

    def spawn(self):
        stub = StubReplica("as-%d" % len(self.stubs))
        self.stubs.append(stub)
        return stub.url, {"stub": stub}

    def restart(self, replica):
        raise NotImplementedError

    def stop(self, replica):
        self.stopped.append(replica.id)

    def close(self):
        for s in self.stubs:
            s.kill()


def test_autoscaler_grows_on_sustained_slo_burn():
    a = StubReplica("a")
    backend = StubBackend()
    gw = _mk_gateway([a], backend=backend)
    scaler = Autoscaler(gw, backend=backend, min_replicas=1,
                        max_replicas=3, slo_p99_ms=100.0, queue_high=50,
                        burn_ticks=2, idle_ticks=4)
    try:
        # synthetic SLO burn: gateway-observed latencies over the SLO
        for _ in range(20):
            gw.metrics.record_request(0.5)   # 500 ms >> 100 ms SLO
        action, sig = scaler.tick()
        assert action is None and sig["slo_burn"]   # hysteresis tick 1
        action, _ = scaler.tick()
        assert action == "up"                       # sustained burn
        assert len(gw.replicas()) == 2
        gw.scrape_once()                            # health-gated join
        assert len(gw.ready_replicas()) == 2
        snap = gw.metrics.snapshot()
        assert snap["scale_ups"] == 1
        assert any(e["event"] == "scale_up" for e in gw.events())
        # burn streak reset after the action: next tick doesn't re-spawn
        action, _ = scaler.tick()
        assert action is None and len(gw.replicas()) == 2
    finally:
        gw.close()
        a.kill()
        backend.close()


def test_autoscaler_queue_depth_burn_signal():
    a = StubReplica("a", queue_depth=20)
    backend = StubBackend()
    gw = _mk_gateway([a], backend=backend)
    scaler = Autoscaler(gw, backend=backend, min_replicas=1,
                        max_replicas=2, slo_p99_ms=0.0, queue_high=8,
                        burn_ticks=1)
    try:
        sig = scaler.evaluate()
        assert sig["queue_burn"] and not sig["slo_burn"]
        action, _ = scaler.tick()
        assert action == "up"
        # at the ceiling: more burn ticks change nothing
        for _ in range(3):
            action, _ = scaler.tick()
            assert action is None
        assert len(gw.replicas()) == 2
    finally:
        gw.close()
        a.kill()
        backend.close()


def test_autoscaler_shrinks_when_idle_not_below_floor():
    a = StubReplica("a")
    backend = StubBackend()
    gw = _mk_gateway([a], backend=backend)
    scaler = Autoscaler(gw, backend=backend, min_replicas=1,
                        max_replicas=3, slo_p99_ms=100.0, queue_high=8,
                        burn_ticks=1, idle_ticks=2)
    try:
        rep2 = scaler.scale_up()
        gw.scrape_once()
        assert len(gw.ready_replicas()) == 2
        # idle: no traffic, zero queues
        action, sig = scaler.tick()
        assert action is None and sig["idle"]
        action, _ = scaler.tick()
        assert action == "down"
        assert len(gw.replicas()) == 1
        assert backend.stopped == [rep2.id]  # newest/least-loaded drained
        snap = gw.metrics.snapshot()
        assert snap["scale_downs"] == 1 and snap["drains"] == 1
        # at the floor: never drains the last replica
        for _ in range(5):
            action, _ = scaler.tick()
            assert action is None
        assert len(gw.replicas()) == 1
    finally:
        gw.close()
        a.kill()
        backend.close()


# ---------------------------------------------------------------------------
# satellites: /drain endpoint, SIGTERM drain, queue-depth gauge, prom
# ---------------------------------------------------------------------------

def test_drain_endpoint_flips_health_and_sheds_new_work():
    with ModelServer(_linear, port=0, buckets=(1, 2),
                     max_latency_ms=1.0) as srv:
        code, body = _get(srv.url + "/healthz")
        assert body["status"] == "ok"
        code, body = _get(srv.url + "/drain")
        assert code == 202 and body["status"] == "draining"
        code, body = _get(srv.url + "/healthz")
        assert body["status"] == "draining"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url + "/predict", {"data": [0.0] * D_IN})
        assert ei.value.code == 503


def test_drain_endpoint_admin_token_guard(monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_ADMIN_TOKEN", "s3cret")
    with ModelServer(_linear, port=0, buckets=(1, 2),
                     max_latency_ms=1.0) as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/drain")
        assert ei.value.code == 403
        code, body = _get(srv.url + "/healthz")
        assert body["status"] == "ok"   # guard refused: still serving
        code, body = _get(srv.url + "/drain",
                          headers={"X-Admin-Token": "s3cret"})
        assert code == 202
        assert _get(srv.url + "/healthz")[1]["status"] == "draining"


def test_sigterm_handler_drains_in_flight_before_stop():
    """Satellite: the SIGTERM handler runs the bounded drain — a request
    in flight when the signal lands completes instead of dropping."""
    release = threading.Event()

    def gated(x):
        release.wait(5.0)
        return _linear(x)

    stopped = threading.Event()
    srv = ModelServer(gated, port=0, buckets=(1, 2),
                      max_latency_ms=1.0).start()
    # signals=() wires the handler without touching process-global
    # dispositions; the test delivers the "signal" directly
    srv.install_drain_handler(signals=(), grace_ms=8000.0,
                              on_stopped=stopped.set)
    result = {}

    def one_request():
        x = np.random.randn(D_IN).astype("float32")
        try:
            result["resp"] = _post(srv.url + "/predict",
                                   {"data": x.tolist()}, timeout=10)
        except Exception as e:  # noqa: BLE001
            result["error"] = repr(e)

    t = threading.Thread(target=one_request)
    t.start()
    time.sleep(0.15)                      # request is gated in the model
    srv._on_drain_signal(signal.SIGTERM, None)
    assert srv.draining                   # flipped before the drain ends
    time.sleep(0.05)
    release.set()                         # model finishes
    t.join(10.0)
    assert stopped.wait(10.0)             # bounded drain ran to the end
    assert "error" not in result, result
    assert result["resp"][0] == 200
    # repeated signal after stop started: no second drain thread
    srv._on_drain_signal(signal.SIGTERM, None)


def test_serving_queue_depth_profiler_row_and_prom_gauge():
    """Satellite: predict lanes export live serving.queue_depth like
    generation lanes already do."""
    m = ServingMetrics(name="serving")
    m.set_queue_depth_fn(lambda: 7)
    assert m.profiler_rows()["serving.queue_depth"] == (7, 0.0)
    with ModelServer(_linear, port=0, buckets=(1, 2),
                     max_latency_ms=1.0) as srv:
        text = srv.prometheus_text()
    assert "mxtpu_serving_queue_depth" in text


def test_gateway_prometheus_exposition():
    a = StubReplica("a")
    gw = _mk_gateway([a])
    try:
        _post(gw.url + "/predict", {"data": [1.0]})
        with urllib.request.urlopen(gw.url + "/metrics.prom",
                                    timeout=5) as resp:
            assert "openmetrics" in resp.headers["Content-Type"]
            text = resp.read().decode()
        assert text.endswith("# EOF\n")
        for family in ("mxtpu_gateway_requests_total",
                       "mxtpu_gateway_failovers_total",
                       "mxtpu_gateway_ready_replicas",
                       "mxtpu_gateway_replica_up",
                       "mxtpu_gateway_replica_queue_depth",
                       "mxtpu_gateway_latency_ms"):
            assert family in text, family
        # per-replica sample carries the replica label plus the mesh
        # size (chips behind the replica; 1 for a single-chip backend)
        assert 'mxtpu_gateway_replica_up{replica="0",mesh="1"} 1' in text
        assert "mxtpu_gateway_replica_chips" in text
        # gateway.* rows reached the profiler aggregate table
        from mxnet_tpu import profiler
        rows = profiler.get_aggregate_stats()
        assert rows["gateway.requests"]["calls"] >= 1
    finally:
        gw.close()
        a.kill()


def test_gateway_event_log_jsonl(tmp_path):
    path = str(tmp_path / "events.jsonl")
    a = StubReplica("a")
    gw = Gateway(replicas=[a.url], scrape_ms=0, event_log=path,
                 retry_policy=_fast_retry())
    gw.start()
    try:
        rid = gw.replicas()[0].id
        gw.mark_draining(rid)
        with open(path) as f:
            events = [json.loads(line) for line in f]
        kinds = [e["event"] for e in events]
        assert "replica_added" in kinds
        assert "replica_up" in kinds
        assert "replica_draining" in kinds
        assert all("t" in e for e in events)
    finally:
        gw.close()
        a.kill()
