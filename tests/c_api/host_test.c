/* Standalone C host driving the runtime through the flat ABI — the
 * language-binding scenario the reference's c_api.h exists for (a Scala/R/
 * Julia frontend is "this program", mechanically generated). Built and run
 * by tests/test_c_api.py. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu_c.h"

#define CHECK(stmt)                                                   \
  do {                                                                \
    if ((stmt) != 0) {                                                \
      fprintf(stderr, "FAIL %s: %s\n", #stmt, MXGetLastError());      \
      return 1;                                                       \
    }                                                                 \
  } while (0)

int main(int argc, char** argv) {
  const char* repo = argc > 1 ? argv[1] : ".";
  CHECK(MXTpuInit(repo));

  int version = 0;
  CHECK(MXGetVersion(&version));
  printf("version=%d\n", version);

  int n_ops = 0;
  const char** names = NULL;
  CHECK(MXListAllOpNames(&n_ops, &names));
  printf("n_ops=%d\n", n_ops);
  if (n_ops < 400) {
    fprintf(stderr, "expected a populated op registry\n");
    return 1;
  }

  int64_t shape[2] = {2, 3};
  NDArrayHandle x = NULL;
  CHECK(MXNDArrayCreate(shape, 2, "float32", &x));

  float host[6] = {-2.0f, -1.0f, 0.0f, 1.0f, 2.0f, 3.0f};
  CHECK(MXNDArraySyncCopyFromCPU(x, host, 6));

  NDArrayHandle outs[4];
  int n_out = 4;
  CHECK(MXImperativeInvoke("relu", &x, 1, NULL, outs, &n_out));
  if (n_out != 1) {
    fprintf(stderr, "relu should have one output\n");
    return 1;
  }

  int ndim = 0;
  int64_t oshape[8];
  CHECK(MXNDArrayGetShape(outs[0], &ndim, oshape, 8));
  if (ndim != 2 || oshape[0] != 2 || oshape[1] != 3) {
    fprintf(stderr, "bad output shape\n");
    return 1;
  }

  float back[6];
  CHECK(MXNDArraySyncCopyToCPU(outs[0], back, 6));
  float want[6] = {0.0f, 0.0f, 0.0f, 1.0f, 2.0f, 3.0f};
  for (int i = 0; i < 6; ++i) {
    if (back[i] != want[i]) {
      fprintf(stderr, "relu mismatch at %d: %f != %f\n", i, back[i], want[i]);
      return 1;
    }
  }

  /* kwargs path: sum over axis 1 */
  n_out = 4;
  NDArrayHandle souts[4];
  CHECK(MXImperativeInvoke("sum", &x, 1, "{\"axis\": 1}", souts, &n_out));
  float sums[2];
  CHECK(MXNDArraySyncCopyToCPU(souts[0], sums, 2));
  if (sums[0] != -3.0f || sums[1] != 6.0f) {
    fprintf(stderr, "sum mismatch: %f %f\n", sums[0], sums[1]);
    return 1;
  }

  CHECK(MXNDArrayWaitAll());
  CHECK(MXNDArrayFree(x));
  CHECK(MXNDArrayFree(outs[0]));
  CHECK(MXNDArrayFree(souts[0]));
  printf("C_API_HOST_OK\n");
  return 0;
}
