/* C-host inference: load an exported model (-symbol.json + .params file
 * CONTENT) through the predict ABI and run a forward pass — the reference
 * deployment story (c_predict_api.cc MXPredCreate/SetInput/Forward/
 * GetOutput; example/image-classification/predict-cpp).
 *
 * Usage: predict_host <repo_root> <symbol.json path> <params path>
 * Prints C_API_PREDICT_OK on success. */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu_c.h"

#define CHECK(x)                                                      \
  do {                                                                \
    if ((x) != 0) {                                                   \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,         \
              MXGetLastError());                                      \
      return 1;                                                       \
    }                                                                 \
  } while (0)

static char* slurp(const char* path, long* out_len) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc((size_t)n + 1);
  if (fread(buf, 1, (size_t)n, f) != (size_t)n) {
    fclose(f);
    free(buf);
    return NULL;
  }
  fclose(f);
  buf[n] = 0;
  if (out_len) *out_len = n;
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: predict_host <repo> <symbol.json> <params>\n");
    return 2;
  }
  CHECK(MXTpuInit(argv[1]));

  long json_len = 0, param_len = 0;
  char* json = slurp(argv[2], &json_len);
  char* params = slurp(argv[3], &param_len);
  if (!json || !params) {
    fprintf(stderr, "cannot read model files\n");
    return 1;
  }

  PredictorHandle pred;
  {
    const char* keys[] = {"data", "softmax_label"};
    int ndims[] = {4, 1};
    int64_t shapes[] = {2, 1, 12, 12, 2};
    CHECK(MXPredCreate(json, params, param_len, "cpu", 2, keys, ndims,
                       shapes, &pred));
  }

  /* deterministic input */
  float input[2 * 1 * 12 * 12];
  for (int i = 0; i < 2 * 144; ++i) {
    input[i] = sinf(0.05f * (float)i);
  }
  CHECK(MXPredSetInput(pred, "data", input, 2 * 144));
  CHECK(MXPredForward(pred));

  const int64_t* oshape = NULL;
  int ondim = 0;
  CHECK(MXPredGetOutputShape(pred, 0, &oshape, &ondim));
  if (ondim != 2 || oshape[0] != 2 || oshape[1] != 10) {
    fprintf(stderr, "bad output shape (%d dims)\n", ondim);
    return 1;
  }

  float out[2 * 10];
  CHECK(MXPredGetOutput(pred, 0, out, 20));
  /* softmax rows must each sum to 1 */
  for (int r = 0; r < 2; ++r) {
    float s = 0.0f;
    for (int c = 0; c < 10; ++c) s += out[r * 10 + c];
    if (fabsf(s - 1.0f) > 1e-3f) {
      fprintf(stderr, "row %d prob sum %.4f\n", r, s);
      return 1;
    }
  }

  /* reshape to a new batch size and run again */
  {
    const char* keys[] = {"data", "softmax_label"};
    int ndims[] = {4, 1};
    int64_t shapes[] = {4, 1, 12, 12, 4};
    CHECK(MXPredReshape(pred, 2, keys, ndims, shapes));
    float big[4 * 144];
    memset(big, 0, sizeof(big));
    CHECK(MXPredSetInput(pred, "data", big, 4 * 144));
    CHECK(MXPredForward(pred));
    CHECK(MXPredGetOutputShape(pred, 0, &oshape, &ondim));
    if (oshape[0] != 4) {
      fprintf(stderr, "reshape failed\n");
      return 1;
    }
  }

  CHECK(MXPredFree(pred));
  free(json);
  free(params);
  printf("C_API_PREDICT_OK\n");
  return 0;
}
