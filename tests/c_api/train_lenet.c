/* C-host training: build LeNet through the symbol ABI, bind an executor,
 * train on synthetic data with SGD via MXImperativeInvoke, and assert the
 * loss drops. This is the "a C host can train a model" proof the reference
 * C ABI gives its language bindings (c_api_executor.cc + the Scala/C++
 * trainers built on it).
 *
 * Also exercises: kvstore init/push/pull (the dist-training client path),
 * NDArray save/load, symbol JSON save, executor introspection.
 *
 * Usage: train_lenet <repo_root> [export_dir]
 * Prints C_API_TRAIN_OK on success. */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu_c.h"

#define CHECK(x)                                                      \
  do {                                                                \
    if ((x) != 0) {                                                   \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,         \
              MXGetLastError());                                      \
      return 1;                                                       \
    }                                                                 \
  } while (0)

/* Compose op(inputs...) with string params into a fresh symbol. */
static int make_op(const char* op, const char* name, int num_param,
                   const char** pk, const char** pv, int num_in,
                   SymbolHandle* in, SymbolHandle* out) {
  const char* empty_keys[8] = {0};
  if (MXSymbolCreateAtomicSymbol(op, num_param, pk, pv, out) != 0) return -1;
  return MXSymbolCompose(*out, name, num_in, empty_keys, in);
}

int main(int argc, char** argv) {
  CHECK(MXTpuInit(argc > 1 ? argv[1] : NULL));
  MXRandomSeed(7);

  /* ---- LeNet-ish: conv-pool-conv-pool-fc-fc-softmax on 8x1x12x12 ---- */
  SymbolHandle data, label, c1, a1, p1, fl, fc1, a2, fc2, net;
  CHECK(MXSymbolCreateVariable("data", &data));
  CHECK(MXSymbolCreateVariable("softmax_label", &label));

  {
    const char* k[] = {"num_filter", "kernel"};
    const char* v[] = {"8", "(3, 3)"};
    SymbolHandle in[] = {data};
    CHECK(make_op("Convolution", "conv1", 2, k, v, 1, in, &c1));
  }
  {
    const char* k[] = {"act_type"};
    const char* v[] = {"tanh"};
    SymbolHandle in[] = {c1};
    CHECK(make_op("Activation", "act1", 1, k, v, 1, in, &a1));
  }
  {
    const char* k[] = {"pool_type", "kernel", "stride"};
    const char* v[] = {"max", "(2, 2)", "(2, 2)"};
    SymbolHandle in[] = {a1};
    CHECK(make_op("Pooling", "pool1", 3, k, v, 1, in, &p1));
  }
  {
    SymbolHandle in[] = {p1};
    CHECK(make_op("Flatten", "flat", 0, NULL, NULL, 1, in, &fl));
  }
  {
    const char* k[] = {"num_hidden"};
    const char* v[] = {"32"};
    SymbolHandle in[] = {fl};
    CHECK(make_op("FullyConnected", "fc1", 1, k, v, 1, in, &fc1));
  }
  {
    const char* k[] = {"act_type"};
    const char* v[] = {"relu"};
    SymbolHandle in[] = {fc1};
    CHECK(make_op("Activation", "act2", 1, k, v, 1, in, &a2));
  }
  {
    const char* k[] = {"num_hidden"};
    const char* v[] = {"10"};
    SymbolHandle in[] = {a2};
    CHECK(make_op("FullyConnected", "fc2", 1, k, v, 1, in, &fc2));
  }
  {
    SymbolHandle in[] = {fc2, label};
    CHECK(make_op("SoftmaxOutput", "softmax", 0, NULL, NULL, 2, in, &net));
  }

  /* symbol introspection */
  int n_args = 0;
  const char** arg_names = NULL;
  CHECK(MXSymbolListArguments(net, &n_args, &arg_names));
  if (n_args < 8) {
    fprintf(stderr, "expected >=8 arguments, got %d\n", n_args);
    return 1;
  }
  const char* json = NULL;
  CHECK(MXSymbolSaveToJSON(net, &json));
  if (strstr(json, "conv1") == NULL) {
    fprintf(stderr, "symbol json missing node\n");
    return 1;
  }

  /* shape inference through the ABI */
  {
    const char* keys[] = {"data", "softmax_label"};
    int ndims[] = {4, 1};
    int64_t shapes[] = {8, 1, 12, 12, 8};
    int in_sz, out_sz, aux_sz, complete;
    const int *in_nd, *out_nd, *aux_nd;
    const int64_t *in_d, *out_d, *aux_d;
    CHECK(MXSymbolInferShape(net, 2, keys, ndims, shapes, 0, &in_sz,
                             &in_nd, &in_d, &out_sz, &out_nd, &out_d,
                             &aux_sz, &aux_nd, &aux_d, &complete));
    if (!complete || out_sz != 1 || out_nd[0] != 2 || out_d[0] != 8 ||
        out_d[1] != 10) {
      fprintf(stderr, "infer_shape wrong: complete=%d out=(%lld,%lld)\n",
              complete, (long long)out_d[0], (long long)out_d[1]);
      return 1;
    }
  }

  /* ---- bind ---- */
  ExecutorHandle exec;
  {
    const char* keys[] = {"data", "softmax_label"};
    int ndims[] = {4, 1};
    int64_t shapes[] = {8, 1, 12, 12, 8};
    CHECK(MXExecutorSimpleBind(net, "cpu", "write", 2, keys, ndims, shapes,
                               &exec));
  }
  int n_exec_args = 0;
  NDArrayHandle* args_arr = NULL;
  CHECK(MXExecutorArgArrays(exec, &n_exec_args, &args_arr));
  /* keep private copies: the tls pointer array is reused by later calls */
  NDArrayHandle arg_h[32];
  for (int i = 0; i < n_exec_args; ++i) arg_h[i] = args_arr[i];
  const char** exec_arg_names = NULL;
  int n_names = 0;
  CHECK(MXExecutorArgNames(exec, &n_names, &exec_arg_names));
  char names_copy[32][64];
  for (int i = 0; i < n_names; ++i) {
    strncpy(names_copy[i], exec_arg_names[i], 63);
    names_copy[i][63] = 0;
  }

  /* ---- init params (uniform +-0.3), fixed synthetic batch ---- */
  srand(13);
  float data_buf[8 * 1 * 12 * 12], label_buf[8];
  for (int i = 0; i < 8 * 144; ++i) {
    data_buf[i] = (float)rand() / (float)RAND_MAX - 0.5f;
  }
  for (int i = 0; i < 8; ++i) label_buf[i] = (float)(i % 10);

  for (int i = 0; i < n_exec_args; ++i) {
    if (strcmp(names_copy[i], "data") == 0) {
      CHECK(MXNDArraySyncCopyFromCPU(arg_h[i], data_buf, 8 * 144));
    } else if (strcmp(names_copy[i], "softmax_label") == 0) {
      CHECK(MXNDArraySyncCopyFromCPU(arg_h[i], label_buf, 8));
    } else {
      int nd = 0;
      int64_t shp[8];
      CHECK(MXNDArrayGetShape(arg_h[i], &nd, shp, 8));
      int64_t sz = 1;
      for (int j = 0; j < nd; ++j) sz *= shp[j];
      float* w = (float*)malloc(sizeof(float) * (size_t)sz);
      for (int64_t j = 0; j < sz; ++j) {
        w[j] = 0.6f * ((float)rand() / (float)RAND_MAX - 0.5f);
      }
      CHECK(MXNDArraySyncCopyFromCPU(arg_h[i], w, sz));
      free(w);
    }
  }

  /* ---- kvstore round-trip on one weight (dist-client path) ---- */
  {
    KVStoreHandle kv;
    CHECK(MXKVStoreCreate("local", &kv));
    const char* t = NULL;
    CHECK(MXKVStoreGetType(kv, &t));
    int rank = -1, size = 0;
    CHECK(MXKVStoreGetRank(kv, &rank));
    CHECK(MXKVStoreGetGroupSize(kv, &size));
    if (strcmp(t, "local") != 0 || rank != 0 || size != 1) {
      fprintf(stderr, "kvstore meta wrong\n");
      return 1;
    }
    const char* kk[] = {"w0"};
    NDArrayHandle vv[] = {arg_h[1]};
    CHECK(MXKVStoreInit(kv, 1, kk, vv));
    CHECK(MXKVStorePush(kv, 1, kk, vv, 0));
    CHECK(MXKVStorePull(kv, 1, kk, vv, 0));
    CHECK(MXKVStoreBarrier(kv));
    CHECK(MXKVStoreFree(kv));
  }

  /* ---- training loop: forward / backward / sgd_update ---- */
  float first_loss = -1.0f, last_loss = -1.0f;
  for (int epoch = 0; epoch < 30; ++epoch) {
    CHECK(MXExecutorForward(exec, 1));
    CHECK(MXExecutorBackward(exec, 0, NULL));

    int n_out = 0;
    NDArrayHandle* outs = NULL;
    CHECK(MXExecutorOutputs(exec, &n_out, &outs));
    NDArrayHandle prob = outs[0];

    float p[8 * 10];
    CHECK(MXNDArraySyncCopyToCPU(prob, p, 80));
    float loss = 0.0f;
    for (int i = 0; i < 8; ++i) {
      float pi = p[i * 10 + (int)label_buf[i]];
      loss += -logf(pi > 1e-8f ? pi : 1e-8f);
    }
    loss /= 8.0f;
    if (epoch == 0) first_loss = loss;
    last_loss = loss;
    CHECK(MXNDArrayFree(prob));

    int n_grads = 0;
    NDArrayHandle* grads_tls = NULL;
    CHECK(MXExecutorGradArrays(exec, &n_grads, &grads_tls));
    NDArrayHandle grad_h[32];
    for (int i = 0; i < n_grads; ++i) grad_h[i] = grads_tls[i];

    for (int i = 0; i < n_exec_args; ++i) {
      if (strcmp(names_copy[i], "data") == 0 ||
          strcmp(names_copy[i], "softmax_label") == 0 ||
          grad_h[i] == NULL) {
        continue;
      }
      NDArrayHandle io[2] = {arg_h[i], grad_h[i]};
      NDArrayHandle upd[2];
      int n_upd = 2;
      CHECK(MXImperativeInvoke("sgd_update", io, 2, "{\"lr\": 0.1}", upd,
                               &n_upd));
      /* write the updated weight back into the bound buffer */
      int nd = 0;
      int64_t shp[8];
      CHECK(MXNDArrayGetShape(upd[0], &nd, shp, 8));
      int64_t sz = 1;
      for (int j = 0; j < nd; ++j) sz *= shp[j];
      float* w = (float*)malloc(sizeof(float) * (size_t)sz);
      CHECK(MXNDArraySyncCopyToCPU(upd[0], w, sz));
      CHECK(MXNDArraySyncCopyFromCPU(arg_h[i], w, sz));
      free(w);
      for (int u = 0; u < n_upd; ++u) MXNDArrayFree(upd[u]);
    }
    for (int i = 0; i < n_grads; ++i) {
      if (grad_h[i]) MXNDArrayFree(grad_h[i]);
    }
  }

  printf("loss %.4f -> %.4f\n", first_loss, last_loss);
  if (!(last_loss < 0.6f * first_loss)) {
    fprintf(stderr, "loss did not drop enough\n");
    return 1;
  }

  /* ---- save params + symbol for the predict host ---- */
  {
    NDArrayHandle save_h[32];
    const char* save_k[32];
    char key_store[32][80];
    int n_save = 0;
    for (int i = 0; i < n_exec_args; ++i) {
      if (strcmp(names_copy[i], "data") == 0 ||
          strcmp(names_copy[i], "softmax_label") == 0) {
        continue;
      }
      snprintf(key_store[n_save], 80, "arg:%s", names_copy[i]);
      save_k[n_save] = key_store[n_save];
      save_h[n_save] = arg_h[i];
      ++n_save;
    }
    const char* outdir = argc > 2 ? argv[2] : "/tmp";
    char params_path[512], sym_path[512];
    snprintf(params_path, sizeof(params_path), "%s/lenet_capi.params",
             outdir);
    snprintf(sym_path, sizeof(sym_path), "%s/lenet_capi-symbol.json",
             outdir);
    CHECK(MXNDArraySave(params_path, n_save, save_h, save_k));
    CHECK(MXSymbolSaveToFile(net, sym_path));

    /* reload round-trip */
    int n_loaded = 0, n_lnames = 0;
    NDArrayHandle* loaded = NULL;
    const char** lnames = NULL;
    CHECK(MXNDArrayLoad(params_path, &n_loaded, &loaded,
                        &n_lnames, &lnames));
    if (n_loaded != n_save || n_lnames != n_save) {
      fprintf(stderr, "save/load count mismatch\n");
      return 1;
    }
    for (int i = 0; i < n_loaded; ++i) MXNDArrayFree(loaded[i]);
  }

  for (int i = 0; i < n_exec_args; ++i) MXNDArrayFree(arg_h[i]);
  CHECK(MXExecutorFree(exec));
  MXSymbolFree(net);

  printf("C_API_TRAIN_OK\n");
  return 0;
}
