"""Pallas kernel tests (interpret mode on CPU; real lowering exercised on
TPU by the driver's bench)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                          _reference_attention,
                                          flash_attention_usable)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    np.random.seed(0)
    B, H, S, D = 2, 2, 256, 64
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    out = flash_attention(q, k, v, causal, True)
    ref = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)


def test_flash_attention_grads_finite():
    np.random.seed(1)
    B, H, S, D = 1, 2, 128, 32
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    g = jax.grad(lambda q: flash_attention(q, q, q, True, True).sum())(q)
    assert np.isfinite(np.asarray(g)).all()


def test_usability_gate():
    assert flash_attention_usable((1, 2, 256, 64))
    assert not flash_attention_usable((1, 2, 100, 64))  # unaligned seq


def test_cross_attention_kv_len_mismatch_takes_xla_path(monkeypatch):
    """Cross-attention (kv_len != q_len) must not reach the pallas kernel,
    whose tiling assumes self-attention layout — the fused op falls back to
    the XLA path and matches the dense reference."""
    from mxnet_tpu.ops import pallas_kernels
    from mxnet_tpu.ops.registry import get_op, invoke

    # the pallas kernel must not be selected regardless of platform
    def _boom(*a, **k):
        raise AssertionError("pallas kernel selected for cross-attention")

    monkeypatch.setattr(pallas_kernels, "flash_attention", _boom)
    np.random.seed(2)
    B, H, Sq, Skv, D = 1, 2, 128, 256, 32
    q = np.random.randn(B, H, Sq, D).astype("float32")
    k = np.random.randn(B, H, Skv, D).astype("float32")
    v = np.random.randn(B, H, Skv, D).astype("float32")
    out = invoke(get_op("_contrib_dot_product_attention"), jnp.asarray(q),
                 jnp.asarray(k), jnp.asarray(v))
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=2e-4)
