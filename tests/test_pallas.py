"""Pallas kernel tests (interpret mode on CPU; real lowering exercised on
TPU by the driver's bench)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                          _reference_attention,
                                          flash_attention_usable)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    np.random.seed(0)
    B, H, S, D = 2, 2, 256, 64
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    out = flash_attention(q, k, v, None, None, causal, 0.0, True)
    ref = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)


def test_flash_attention_grads_finite():
    np.random.seed(1)
    B, H, S, D = 1, 2, 128, 32
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    g = jax.grad(lambda q: flash_attention(q, q, q, None, None, True, 0.0, True).sum())(q)
    assert np.isfinite(np.asarray(g)).all()


def test_usability_gate():
    assert flash_attention_usable((1, 2, 256, 64))
    assert not flash_attention_usable((1, 2, 100, 64))  # unaligned seq


def test_cross_attention_kv_len_mismatch_takes_xla_path(monkeypatch):
    """Cross-attention (kv_len != q_len) must not reach the pallas kernel,
    whose tiling assumes self-attention layout — the fused op falls back to
    the XLA path and matches the dense reference."""
    from mxnet_tpu.ops import pallas_kernels
    from mxnet_tpu.ops.registry import get_op, invoke

    # the pallas kernel must not be selected regardless of platform
    def _boom(*a, **k):
        raise AssertionError("pallas kernel selected for cross-attention")

    monkeypatch.setattr(pallas_kernels, "flash_attention", _boom)
    np.random.seed(2)
    B, H, Sq, Skv, D = 1, 2, 128, 256, 32
    q = np.random.randn(B, H, Sq, D).astype("float32")
    k = np.random.randn(B, H, Skv, D).astype("float32")
    v = np.random.randn(B, H, Skv, D).astype("float32")
    out = invoke(get_op("_contrib_dot_product_attention"), jnp.asarray(q),
                 jnp.asarray(k), jnp.asarray(v))
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=2e-4)


def test_unaligned_seq_falls_back_and_matches_oracle():
    """S % 128 != 0 and D > 256 must take the XLA fallback inside the
    fused attention op and still match the dense oracle (VERDICT r1 weak
    item: fallback boundaries untested)."""
    from mxnet_tpu.ops.registry import get_op
    op = get_op("_contrib_dot_product_attention")
    np.random.seed(2)
    for (S, D) in [(100, 64), (128, 512)]:
        assert not flash_attention_usable((1, 2, S, D))
        q = jnp.asarray(np.random.randn(1, 2, S, D).astype("float32"))
        k = jnp.asarray(np.random.randn(1, 2, S, D).astype("float32"))
        v = jnp.asarray(np.random.randn(1, 2, S, D).astype("float32"))
        ref = _reference_attention(q, k, v, False)
        out = op.fn(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)


def test_flash_attention_single_tile_minimum():
    """Smallest legal tile (S=128): kernel path still matches oracle."""
    np.random.seed(3)
    q = jnp.asarray(np.random.randn(1, 1, 128, 32).astype("float32"))
    out = flash_attention(q, q, q, None, None, False, 0.0, True)
    ref = _reference_attention(q, q, q, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)


def test_flash_attention_causal_masks_future():
    """First query position may only see the first kv position: its output
    row must equal v[0] exactly under causal masking."""
    np.random.seed(4)
    q = jnp.asarray(np.random.randn(1, 1, 128, 32).astype("float32"))
    v = jnp.asarray(np.random.randn(1, 1, 128, 32).astype("float32"))
    out = flash_attention(q, q, v, None, None, True, 0.0, True)
    np.testing.assert_allclose(np.asarray(out)[0, 0, 0],
                               np.asarray(v)[0, 0, 0], atol=1e-4)


# ---------------------------------------------------------------- new in r4:
# key padding mask + in-kernel dropout (VERDICT r3 item 3: flash attention
# must carry BERT's real training configuration)

def _rand(shape, seed):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype("float32"))


def test_flash_attention_kv_mask_matches_reference():
    B, H, S, D = 2, 2, 256, 32
    q, k, v = (_rand((B, H, S, D), i) for i in range(3))
    # batch 0 keeps 160 keys, batch 1 keeps all
    lens = np.array([160, S])
    kv_mask = jnp.asarray((np.arange(S)[None, :] < lens[:, None])
                          .astype("int32"))
    out = flash_attention(q, k, v, kv_mask, None, False, 0.0, True)
    ref = _reference_attention(q, k, v, False, kv_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)


def test_flash_attention_fully_masked_rows_zero():
    """A batch whose keep-mask is all zero must produce zero output (and
    finite gradients), not garbage from the epsilon-guarded normalizer."""
    B, H, S, D = 1, 1, 128, 32
    q, k, v = (_rand((B, H, S, D), 10 + i) for i in range(3))
    kv_mask = jnp.zeros((B, S), jnp.int32)
    out = flash_attention(q, k, v, kv_mask, None, False, 0.0, True)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)
    g = jax.grad(lambda q: flash_attention(q, k, v, kv_mask, None, False,
                                           0.0, True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), 0.0, atol=1e-6)


@pytest.mark.parametrize("causal,masked", [(False, False), (True, False),
                                           (False, True)])
def test_flash_attention_pallas_backward_matches_xla(causal, masked):
    """The hand-written dq/dkdv kernels must agree with XLA autodiff of
    the dense formulation (dropout off)."""
    B, H, S, D = 1, 2, 256, 32
    q, k, v = (_rand((B, H, S, D), 20 + i) for i in range(3))
    kv_mask = None
    if masked:
        kv_mask = jnp.asarray(
            (np.arange(S)[None, :] < 192).astype("int32"))
    g_out = _rand((B, H, S, D), 30)

    def fa(q, k, v):
        return flash_attention(q, k, v, kv_mask, None, causal, 0.0, True)

    def ref(q, k, v):
        return _reference_attention(q, k, v, causal, kv_mask)

    _, vjp_fa = jax.vjp(fa, q, k, v)
    _, vjp_ref = jax.vjp(ref, q, k, v)
    for a, b, name in zip(vjp_fa(g_out), vjp_ref(g_out), "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2,
                                   rtol=5e-2, err_msg="d%s" % name)


def test_flash_attention_dropout_statistics_and_determinism():
    B, H, S, D = 1, 2, 256, 32
    q, k, v = (_rand((B, H, S, D), 40 + i) for i in range(3))
    seed = jnp.asarray(1234, jnp.int32)
    out1 = flash_attention(q, k, v, None, seed, False, 0.5, True)
    out2 = flash_attention(q, k, v, None, seed, False, 0.5, True)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    out3 = flash_attention(q, k, v, None, jnp.asarray(99, jnp.int32),
                           False, 0.5, True)
    assert np.abs(np.asarray(out1) - np.asarray(out3)).max() > 1e-3

    # E[dropout(P)] = P: the mean over many heads/rows should track the
    # no-dropout output loosely
    ref = flash_attention(q, k, v, None, None, False, 0.0, True)
    diff = np.abs(np.asarray(out1).mean() - np.asarray(ref).mean())
    assert diff < 0.05


def test_flash_attention_dropout_grad_consistent_with_forward():
    """Directional finite difference: with a FIXED seed the dropped
    attention is a deterministic function, so its custom-vjp gradient must
    predict f(q+eps*u) - f(q-eps*u). This catches fwd/bwd keep-bit
    mismatches (the failure mode of regenerated-RNG backward kernels)."""
    B, H, S, D = 1, 1, 128, 16
    q, k, v = (_rand((B, H, S, D), 50 + i) for i in range(3))
    seed = jnp.asarray(7, jnp.int32)
    u = np.array(_rand((B, H, S, D), 60))
    u /= np.linalg.norm(u)
    un = jnp.asarray(u)

    def f(qq):
        return flash_attention(qq, k, v, None, seed, False, 0.3,
                               True).sum()

    g = jax.grad(f)(q)
    directional = float(jnp.vdot(g, un))
    eps = 1e-2
    fd = (float(f(q + eps * un)) - float(f(q - eps * un))) / (2 * eps)
    np.testing.assert_allclose(directional, fd, rtol=2e-2, atol=2e-3)


def test_dispatch_reduces_bert_mask(monkeypatch):
    """(B,1,1,T) keep-masks must reach the pallas kernel as a (B,T) kv
    mask when a TPU is present (simulated here)."""
    from mxnet_tpu.ops import nn as nn_ops
    from mxnet_tpu.ops import pallas_kernels as pk
    captured = {}

    def fake_flash(q, k, v, kv_mask, seed, causal, dropout,
                   interpret=False):
        captured["kv_mask"] = kv_mask
        captured["dropout"] = dropout
        return _reference_attention(q, k, v, causal, kv_mask)

    monkeypatch.setattr(nn_ops, "jax", jax)
    monkeypatch.setattr(pk, "flash_attention", fake_flash)

    class _FakeDev:
        platform = "tpu"

    monkeypatch.setattr(jax, "devices", lambda: [_FakeDev()])
    B, H, S, D = 2, 2, 128, 16
    q, k, v = (_rand((B, H, S, D), 70 + i) for i in range(3))
    mask4 = jnp.ones((B, 1, 1, S), jnp.int32)
    out = nn_ops.dot_product_attention(q, k, v, mask=mask4)
    assert captured["kv_mask"].shape == (B, S)
    ref = _reference_attention(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3,
                               rtol=2e-3)


# ----------------------------------------------------- head-fused BSHD (r4)

def _to_bhsd(x):
    return jnp.transpose(x, (0, 2, 1, 3))


@pytest.mark.parametrize("causal,masked", [(False, False), (True, False),
                                           (False, True)])
def test_bshd_kernel_matches_reference(causal, masked):
    """Head-fused (B,S,H,D) kernel: forward AND both backward kernels
    agree with the dense oracle (transposed for comparison)."""
    from mxnet_tpu.ops.pallas_kernels import flash_attention_bshd
    B, S, H, D = 2, 256, 4, 32
    q, k, v = (_rand((B, S, H, D), 80 + i) for i in range(3))
    kv_mask = None
    if masked:
        kv_mask = jnp.asarray(
            (np.arange(S)[None, :] < 192).astype("int32")).repeat(B, 0)
    out = flash_attention_bshd(q, k, v, kv_mask, None, causal, 0.0, True)
    ref = _reference_attention(_to_bhsd(q), _to_bhsd(k), _to_bhsd(v),
                               causal, kv_mask)
    np.testing.assert_allclose(np.asarray(_to_bhsd(out)), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)

    g_out = _rand((B, S, H, D), 90)
    _, vjp = jax.vjp(lambda q, k, v: flash_attention_bshd(
        q, k, v, kv_mask, None, causal, 0.0, True), q, k, v)
    _, vjp_r = jax.vjp(lambda q, k, v: _to_bhsd(_reference_attention(
        _to_bhsd(q), _to_bhsd(k), _to_bhsd(v), causal, kv_mask)), q, k, v)
    for a, b, n in zip(vjp(g_out), vjp_r(g_out), "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2,
                                   rtol=5e-2, err_msg="d%s" % n)


def test_bshd_dropout_deterministic_and_grad_consistent():
    from mxnet_tpu.ops.pallas_kernels import flash_attention_bshd
    B, S, H, D = 1, 128, 2, 64
    q, k, v = (_rand((B, S, H, D), 95 + i) for i in range(3))
    seed = jnp.asarray(11, jnp.int32)
    o1 = flash_attention_bshd(q, k, v, None, seed, False, 0.3, True)
    o2 = flash_attention_bshd(q, k, v, None, seed, False, 0.3, True)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))

    u = np.array(_rand((B, S, H, D), 99))
    u /= np.linalg.norm(u)
    un = jnp.asarray(u)

    def f(qq):
        return flash_attention_bshd(qq, k, v, None, seed, False, 0.3,
                                    True).sum()
    directional = float(jnp.vdot(jax.grad(f)(q), un))
    eps = 1e-2
    fd = (float(f(q + eps * un)) - float(f(q - eps * un))) / (2 * eps)
    np.testing.assert_allclose(directional, fd, rtol=3e-2, atol=3e-3)


def test_bshd_usability_gate_and_fallback():
    """H*D not a multiple of 128 must fall back to the BHSD path and
    still match the oracle through the fused op."""
    from mxnet_tpu.ops.pallas_kernels import flash_attention_bshd_usable
    from mxnet_tpu.ops import nn as nn_ops
    assert flash_attention_bshd_usable((2, 256, 4, 32), 32)
    assert not flash_attention_bshd_usable((2, 256, 3, 20), 20)  # HD=60
    assert not flash_attention_bshd_usable((2, 100, 4, 32), 32)  # seq
    B, S, H, D = 1, 128, 3, 20
    q, k, v = (_rand((B, S, H, D), 70 + i) for i in range(3))
    out = nn_ops.dot_product_attention.fn(q, k, v, layout="BSHD")
    ref = _to_bhsd(_reference_attention(_to_bhsd(q), _to_bhsd(k),
                                        _to_bhsd(v), False))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)
