"""Pallas kernel tests (interpret mode on CPU; real lowering exercised on
TPU by the driver's bench)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                          _reference_attention,
                                          flash_attention_usable)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    np.random.seed(0)
    B, H, S, D = 2, 2, 256, 64
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    out = flash_attention(q, k, v, causal, True)
    ref = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)


def test_flash_attention_grads_finite():
    np.random.seed(1)
    B, H, S, D = 1, 2, 128, 32
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    g = jax.grad(lambda q: flash_attention(q, q, q, True, True).sum())(q)
    assert np.isfinite(np.asarray(g)).all()


def test_usability_gate():
    assert flash_attention_usable((1, 2, 256, 64))
    assert not flash_attention_usable((1, 2, 100, 64))  # unaligned seq


def test_cross_attention_kv_len_mismatch_takes_xla_path(monkeypatch):
    """Cross-attention (kv_len != q_len) must not reach the pallas kernel,
    whose tiling assumes self-attention layout — the fused op falls back to
    the XLA path and matches the dense reference."""
    from mxnet_tpu.ops import pallas_kernels
    from mxnet_tpu.ops.registry import get_op, invoke

    # the pallas kernel must not be selected regardless of platform
    def _boom(*a, **k):
        raise AssertionError("pallas kernel selected for cross-attention")

    monkeypatch.setattr(pallas_kernels, "flash_attention", _boom)
    np.random.seed(2)
    B, H, Sq, Skv, D = 1, 2, 128, 256, 32
    q = np.random.randn(B, H, Sq, D).astype("float32")
    k = np.random.randn(B, H, Skv, D).astype("float32")
    v = np.random.randn(B, H, Skv, D).astype("float32")
    out = invoke(get_op("_contrib_dot_product_attention"), jnp.asarray(q),
                 jnp.asarray(k), jnp.asarray(v))
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=2e-4)


def test_unaligned_seq_falls_back_and_matches_oracle():
    """S % 128 != 0 and D > 256 must take the XLA fallback inside the
    fused attention op and still match the dense oracle (VERDICT r1 weak
    item: fallback boundaries untested)."""
    from mxnet_tpu.ops.registry import get_op
    op = get_op("_contrib_dot_product_attention")
    np.random.seed(2)
    for (S, D) in [(100, 64), (128, 512)]:
        assert not flash_attention_usable((1, 2, S, D))
        q = jnp.asarray(np.random.randn(1, 2, S, D).astype("float32"))
        k = jnp.asarray(np.random.randn(1, 2, S, D).astype("float32"))
        v = jnp.asarray(np.random.randn(1, 2, S, D).astype("float32"))
        ref = _reference_attention(q, k, v, False)
        out = op.fn(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)


def test_flash_attention_single_tile_minimum():
    """Smallest legal tile (S=128): kernel path still matches oracle."""
    np.random.seed(3)
    q = jnp.asarray(np.random.randn(1, 1, 128, 32).astype("float32"))
    out = flash_attention(q, q, q, False, True)
    ref = _reference_attention(q, q, q, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)


def test_flash_attention_causal_masks_future():
    """First query position may only see the first kv position: its output
    row must equal v[0] exactly under causal masking."""
    np.random.seed(4)
    q = jnp.asarray(np.random.randn(1, 1, 128, 32).astype("float32"))
    v = jnp.asarray(np.random.randn(1, 1, 128, 32).astype("float32"))
    out = flash_attention(q, q, v, True, True)
    np.testing.assert_allclose(np.asarray(out)[0, 0, 0],
                               np.asarray(v)[0, 0, 0], atol=1e-4)
