"""Pallas kernel tests (interpret mode on CPU; real lowering exercised on
TPU by the driver's bench)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_kernels import (flash_attention,
                                          _reference_attention,
                                          flash_attention_usable)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    np.random.seed(0)
    B, H, S, D = 2, 2, 256, 64
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    out = flash_attention(q, k, v, causal, True)
    ref = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                               rtol=2e-2)


def test_flash_attention_grads_finite():
    np.random.seed(1)
    B, H, S, D = 1, 2, 128, 32
    q = jnp.asarray(np.random.randn(B, H, S, D).astype("float32"))
    g = jax.grad(lambda q: flash_attention(q, q, q, True, True).sum())(q)
    assert np.isfinite(np.asarray(g)).all()


def test_usability_gate():
    assert flash_attention_usable((1, 2, 256, 64))
    assert not flash_attention_usable((1, 2, 100, 64))  # unaligned seq
