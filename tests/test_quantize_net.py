"""quantize_net int8 inference path — semantics from reference
`python/mxnet/contrib/quantization.py` quantize_net +
`tests/python/quantization/test_quantization.py`: quantized network must
track the float network within int8 tolerance, with static ranges after
calibration."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.contrib.quantization import quantize_net


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    return net


def test_quantize_net_dense_matches_float():
    net = _mlp()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 10).astype("float32"))
    ref = net(x).asnumpy()
    qnet = quantize_net(net)
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() < 0.05 * scale + 0.05


def test_quantize_net_runs_int8_ops():
    """The swapped blocks must hold int8 weights, not dequantized floats."""
    net = _mlp()
    net(mx.nd.zeros((1, 10)))  # resolve deferred shapes
    quantize_net(net)
    blocks = list(net._children.values())
    assert all(b._wq.asnumpy().dtype == np.int8 for b in blocks)


def test_quantize_net_conv_and_calibration():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1))
    net.add(gluon.nn.Activation("relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.randn(2, 3, 8, 8).astype("float32"))
    ref = net(x).asnumpy()
    calib = [mx.nd.array(rng.randn(2, 3, 8, 8).astype("float32"))
             for _ in range(3)] + [x]
    qnet = quantize_net(net, calib_data=calib, calib_mode="naive")
    # ranges frozen after calibration
    conv = next(iter(net._children.values()))
    assert conv._range is not None and not conv._calibrating
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() < 0.08 * scale + 0.08


def test_quantize_net_exclude_layers():
    net = _mlp()
    net(mx.nd.zeros((1, 10)))
    names = [b.name for b in net._children.values()]
    quantize_net(net, exclude_layers=[names[0]])
    blocks = list(net._children.values())
    assert isinstance(blocks[0], gluon.nn.Dense)      # kept float
    assert not isinstance(blocks[1], gluon.nn.Dense)  # swapped


def test_quantized_net_serializes(tmp_path):
    """save_parameters on a quantized net must carry the int8 weights,
    weight ranges AND the calibrated activation ranges; a freshly
    quantized net that load_parameters the file must produce identical
    outputs (round-2 advisor finding: plain attributes were dropped)."""
    rng = np.random.RandomState(7)
    x = mx.nd.array(rng.randn(4, 10).astype("float32"))
    calib = [mx.nd.array(rng.randn(4, 10).astype("float32"))
             for _ in range(2)]

    net = _mlp()
    net(x)
    qnet = quantize_net(net, calib_data=calib, calib_mode="naive")
    ref = qnet(x).asnumpy()
    f = str(tmp_path / "q.params")
    qnet.save_parameters(f)

    # the file must actually contain the quantized tensors
    loaded = mx.nd.load(f)
    assert any("qweight" in k for k in loaded)
    assert any("wrange" in k for k in loaded)
    assert any("calib" in k for k in loaded)

    # a second net quantized WITHOUT calibration picks the ranges up
    # from the checkpoint
    net2 = _mlp()
    net2(x)
    qnet2 = quantize_net(net2)
    qnet2.load_parameters(f)
    out = qnet2(x).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
