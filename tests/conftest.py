"""Test harness: run the whole suite on a virtual 8-device CPU mesh.

Mirrors the reference's device-retargeting test pattern
(`tests/python/unittest/common.py` + `mx.test_utils.default_context()`):
one suite, device chosen by environment. XLA-CPU is the oracle; the driver
separately exercises the real TPU chip.

NOTE: platform selection must go through jax.config.update — in this image a
PJRT plugin for the TPU tunnel is registered at interpreter startup and has
already captured JAX_PLATFORMS, so mutating os.environ in conftest is too
late. XLA_FLAGS is still read lazily at first backend init, so setting it
here (before any jax computation) works.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
