"""Test harness: run the whole suite on a virtual 8-device CPU mesh.

Mirrors the reference's device-retargeting test pattern
(`tests/python/unittest/common.py` + `mx.test_utils.default_context()`):
one suite, device chosen by environment. XLA-CPU is the oracle; the driver
separately exercises the real TPU chip.

NOTE: platform selection must go through jax.config.update — in this image a
PJRT plugin for the TPU tunnel is registered at interpreter startup and has
already captured JAX_PLATFORMS, so mutating os.environ in conftest is too
late. XLA_FLAGS is still read lazily at first backend init, so setting it
here (before any jax computation) works.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Same-suite device retargeting (reference test_utils.py:58
# default_context + tests/python/gpu/test_operator_gpu.py pattern):
# MXTPU_TEST_PLATFORM=tpu runs this suite on the real chip — the
# TPU-vs-CPU consistency sweep (tools/consistency_sweep.py) — with f32
# matmul precision pinned to "highest" so float32 semantics match the
# XLA-CPU oracle (TPU default would use bf16 MXU passes).
if os.environ.get("MXTPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
else:
    jax.config.update("jax_default_matmul_precision", "highest")

    # Device-tolerance floor, the reference's check_consistency pattern
    # (python/mxnet/test_utils.py: GPU fp32 compares at 1e-3): oracle
    # assertions written against XLA-CPU exactness get the accelerator
    # tolerance when the suite retargets the chip (TPU transcendental
    # approximations differ by ~1e-4 rel).
    import numpy as _np
    import numpy.testing as _npt
    _orig_allclose = _npt.assert_allclose

    def _tpu_allclose(actual, desired, rtol=1e-7, atol=0, *args, **kwargs):
        # Floor only floating-point comparisons that didn't ask for
        # exactness: rtol=0 is an explicit exact-match intent and integer
        # comparisons must stay bitwise — only default-ish float tolerances
        # get the accelerator floor.
        a, d = _np.asarray(actual), _np.asarray(desired)
        floaty = a.dtype.kind in "fc" or d.dtype.kind in "fc"
        if floaty and rtol != 0:
            rtol, atol = max(rtol, 1e-3), max(atol, 1e-5)
        return _orig_allclose(actual, desired, rtol=rtol, atol=atol,
                              *args, **kwargs)

    _npt.assert_allclose = _tpu_allclose

    # Optionally floor plain np.allclose too (reference check_consistency
    # applies the device tolerance to every comparison) — but patching the
    # GLOBAL np.allclose can mask intentionally-tight asserts, so it is
    # opt-in for the chip sweep (tools/consistency_sweep.py sets it),
    # not ambient for every TPU-targeted run.
    if os.environ.get("MXTPU_TEST_ALLCLOSE_FLOOR", "0") == "1":
        _orig_np_allclose = _np.allclose

        def _tpu_np_allclose(a, b, rtol=1e-5, atol=1e-8, **kw):
            aa, bb = _np.asarray(a), _np.asarray(b)
            floaty = aa.dtype.kind in "fc" or bb.dtype.kind in "fc"
            if floaty and rtol != 0:
                rtol, atol = max(rtol, 1e-3), max(atol, 1e-5)
            return _orig_np_allclose(a, b, rtol=rtol, atol=atol, **kw)

        _np.allclose = _tpu_np_allclose

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long end-to-end tests excluded from the tier-1 sweep "
        "(run explicitly with -m slow)")
    config.addinivalue_line(
        "markers",
        "chaos: tests that arm fault-injection points "
        "(mxnet_tpu.resilience.chaos) — deselect with -m 'not chaos' when "
        "debugging unrelated failures")


@pytest.fixture(autouse=True)
def _seed_everything():
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
