"""Elastic 3D parallelism — the sharding planner (ISSUE-15 acceptance).

Unit level: the placement search (feasibility gates, memory-constrained
expert sharding, cost ordering, forced plans, serialization, the
supervisor's planner-delegated device re-spread), the plan threading
through ShardedTrainer/DeviceFeed/GuardedStep, checkpoint plan
recording + re-plan accounting + the typed PlanMismatchError, the new
``stall`` chaos kind, and CollectiveWatchdog coverage over the pipeline
/ MoE dispatch collectives (hung stage -> CollectiveTimeout + /healthz
degradation, never a silent wedge).

Process level: a supervised dp x pp x ep MoE job (tests/dist/
planner_worker.py) loses a host to injected ``host_loss``; the
supervisor evicts, re-forms at world-1 with a planner re-spread pool,
the restore RE-PLANS onto the new placement, and the resumed trajectory
is bitwise-equal to uninterrupted restore-and-replay from the same
snapshot at the surviving topology.
"""
import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel
from mxnet_tpu.models.moe_transformer import moe_lm_tiny
from mxnet_tpu.parallel import planner
from mxnet_tpu.parallel.planner import (ModelProfile, PlanError,
                                        PlanMismatchError, ShardingPlan,
                                        plan_sharding, respread)
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.elastic import CollectiveTimeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "launch.py")
WORKER = os.path.join(REPO, "tests", "dist", "planner_worker.py")


@pytest.fixture(autouse=True)
def _clean_chaos_and_alarms():
    from mxnet_tpu.resilience import elastic
    chaos.clear()
    elastic.clear_collective_alarm()
    yield
    chaos.clear()
    elastic.clear_collective_alarm()


def _profile(dense=1 << 20, stage=1 << 20, expert=1 << 24, stages=2,
             experts=4, batch=8, seq=16, d_model=32):
    return ModelProfile(dense_bytes=dense, stage_bytes=stage,
                        expert_bytes=expert, n_stages=stages,
                        n_experts=experts, batch=batch, seq=seq,
                        d_model=d_model)


# ---------------------------------------------------------------------------
# planner unit: search, feasibility, cost, force, serialization, respread
# ---------------------------------------------------------------------------

def test_plan_covers_devices_and_respects_divisibility():
    p = plan_sharding(8, _profile())
    assert p.dp * p.pp * p.ep * p.sp == p.n_devices == 8
    assert 2 % p.pp == 0 and 4 % p.ep == 0
    assert 8 % (p.dp * p.ep) == 0  # batch divisible over the data axes


def test_memory_gate_forces_expert_sharding():
    """The memory-constrained MoE config: experts dominate, the budget
    excludes full replication -> the planner must shard the expert axis;
    pure-dp is infeasible at the same budget."""
    prof = _profile(expert=1 << 26)
    budget = ShardingPlan(dp=8).memory_per_device(prof) // 2
    p = plan_sharding(8, prof, hbm_bytes=budget)
    assert p.ep > 1 or p.pp > 1
    assert p.memory_per_device(prof) <= budget
    assert ShardingPlan(dp=8).feasible(prof, hbm_bytes=budget) is not None


def test_no_feasible_placement_is_typed_and_named():
    """experts x memory that cannot factor over the pool: a PlanError
    carrying every candidate's rejection reason, not a bare assert."""
    prof = _profile(expert=1 << 30, experts=3)  # ep in {1, 3}; 3 !| 8
    with pytest.raises(PlanError, match="no feasible placement"):
        plan_sharding(8, prof, hbm_bytes=1 << 20)


def test_cost_prefers_dp_for_dense_small_models():
    """Tiny params, fat batch: dp's allreduce is cheap, ep/pp would move
    activation volume for nothing -> pure dp wins the cost ordering."""
    prof = _profile(dense=1 << 10, stage=1 << 10, expert=1 << 10,
                    batch=64, seq=128, d_model=256)
    p = plan_sharding(8, prof)
    assert (p.dp, p.pp, p.ep) == (8, 1, 1)


def test_forced_plan_string_dict_and_env(monkeypatch):
    prof = _profile()
    p = plan_sharding(8, prof, force="dp=2,pp=2,ep=2")
    assert (p.dp, p.pp, p.ep, p.sp) == (2, 2, 2, 1)
    p2 = plan_sharding(8, prof, force={"dp": 4, "ep": 2})
    assert (p2.dp, p2.ep) == (4, 2)
    monkeypatch.setenv("MXNET_PLAN_FORCE", "dp=4,pp=2")
    p3 = plan_sharding(8, prof)
    assert (p3.dp, p3.pp) == (4, 2)
    # forced but infeasible/ill-covering placements are still validated
    with pytest.raises(PlanError, match="infeasible"):
        plan_sharding(8, prof, force="dp=1,pp=1,ep=8")  # 8 !| 4 experts
    with pytest.raises(PlanError, match="covers"):
        plan_sharding(8, prof, force="dp=2,pp=2")
    with pytest.raises(PlanError):
        plan_sharding(8, prof, force="qq=8")


def test_plan_serialization_roundtrip_and_equality():
    p = ShardingPlan(dp=2, pp=2, ep=2)
    d = p.to_dict()
    assert d == {"dp": 2, "pp": 2, "ep": 2, "sp": 1, "n_devices": 8}
    assert ShardingPlan.from_dict(d) == p
    assert ShardingPlan.from_dict(json.loads(json.dumps(d))) == p
    assert p != ShardingPlan(dp=4, pp=2, ep=1)
    assert "dp2" in p.describe() and "ep2" in p.describe()
    assert p.multi_axis and not ShardingPlan(dp=8).multi_axis
    with pytest.raises(PlanError):
        ShardingPlan(dp=0)
    with pytest.raises(PlanError):
        ShardingPlan(dp=2, pp=2, n_devices=8)


def test_seq_parallel_axis_opt_in():
    prof = _profile(dense=1 << 28, seq=64)  # fat replicated params:
    # dp allreduce dominates, sp rotation is the cheap way to use devices
    prof.seq_parallel = True
    p = plan_sharding(8, prof)
    assert p.sp > 1
    off = _profile(dense=1 << 28, seq=64)
    assert plan_sharding(8, off).sp == 1  # never without the opt-in


def test_respread_is_planner_factorable():
    """The supervisor's post-eviction spread: power-of-two per-worker
    pools, so the worker-side axis search always has cofactors — the
    un-factorable-mesh fix for pp/ep jobs re-formed at world-1."""
    assert respread(8, 2) == 4
    assert respread(8, 1) == 8
    assert respread(8, 3) == 2      # not 8//3 with a remainder fiction
    assert respread(6, 1) == 4      # rounded DOWN to a factorable pool
    assert respread(1, 5) == 1
    for total in (1, 2, 3, 4, 6, 8, 12, 16):
        for world in (1, 2, 3, 4):
            per = respread(total, world)
            assert per >= 1 and per & (per - 1) == 0  # power of two


def test_profile_from_block_naming_convention():
    net = moe_lm_tiny()
    net.initialize(mx.init.Xavier())
    net(nd.zeros((1, 4), dtype="int32"))
    prof = net.profile(batch=8, seq=16)
    assert prof.n_stages == 2 and prof.n_experts == 4
    assert prof.expert_bytes > 0 and prof.stage_bytes > 0
    assert prof.dense_bytes > 0  # embeddings/head are unstacked
    assert prof.token_bytes == 8 * 16 * 32 * 4


# ---------------------------------------------------------------------------
# plan threading: trainer / feed / guarded step / mesh
# ---------------------------------------------------------------------------

def _moe_trainer(plan, optimizer="adam"):
    mx.random.seed(0)
    np.random.seed(0)
    net = moe_lm_tiny()
    net.initialize(mx.init.Xavier())
    net(nd.zeros((1, 4), dtype="int32"))
    return parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer,
        {"learning_rate": 1e-2}, plan=plan)


def _moe_batches(n, seed=3):
    rng = np.random.RandomState(seed)
    return [(nd.array(rng.randint(0, 64, (8, 16)).astype("int32")),
             nd.array(rng.randint(0, 64, (8, 16)).astype("float32")))
            for _ in range(n)]


def test_trainer_builds_from_plan_and_matches_pure_dp():
    """The end-to-end thread: mesh, batch axes and param rules all come
    from the plan; the 3D placement computes the same math as pure dp
    (same-placement runs are bitwise; across placements the collective
    order differs, so compare to float tolerance)."""
    t3 = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))
    assert t3._batch_axes == ("dp", "ep")
    assert t3.plan.describe() == "dp2·pp2·ep2·sp1"
    assert dict(t3.mesh.shape)["pp"] == 2 and dict(t3.mesh.shape)["ep"] == 2
    # expert params landed sharded over (pp, ep): 1/4 of the tensor per
    # device; stage params over pp only
    for p, v in zip(t3._params, t3._values):
        if "stack_expert_" in p.name:
            shard = v.sharding.shard_shape(v.shape)
            assert shard[0] == v.shape[0] // 2      # pp
            assert shard[1] == v.shape[1] // 2      # ep
        elif "stack_" in p.name:
            assert v.sharding.shard_shape(v.shape)[0] == v.shape[0] // 2
    tdp = _moe_trainer(ShardingPlan(dp=8))
    for x, y in _moe_batches(3):
        a = float(t3.step(x, y).asnumpy())
        b = float(tdp.step(x, y).asnumpy())
        assert a == pytest.approx(b, rel=1e-5)


def test_same_placement_replay_is_bitwise():
    a = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))
    b = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))
    la = [float(a.step(x, y).asnumpy()) for x, y in _moe_batches(3)]
    lb = [float(b.step(x, y).asnumpy()) for x, y in _moe_batches(3)]
    assert la == lb


def test_device_feed_and_step_stream_use_plan_axes():
    from mxnet_tpu.parallel.datafeed import DeviceFeed

    plan = ShardingPlan(dp=2, pp=2, ep=2)
    t = _moe_trainer(plan)
    feed = DeviceFeed(_moe_batches(4), plan=plan, name="plan_feed")
    try:
        x, _y = next(iter(feed))
        spec = x[0].sharding.spec
        assert tuple(spec)[0] == ("dp", "ep")
        losses = t.step_stream(feed, steps=3, chunk=2)
        assert losses.shape == (3,) and t._t == 3
    finally:
        feed.close()


def test_guarded_step_rides_the_plan():
    from mxnet_tpu.resilience.guardrails import GuardedStep

    t = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))
    g = GuardedStep(t)
    try:
        for x, y in _moe_batches(2):
            loss = g.step(x, y)
        assert np.isfinite(float(loss.asnumpy()))
        assert g._plan is t.plan  # checkpoint save sees the plan through
        assert t._t == 2
    finally:
        g.close()


# ---------------------------------------------------------------------------
# checkpoint: plan recorded, re-plan counted, typed mismatch
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_records_plan_and_counts_replan(tmp_path):
    from mxnet_tpu.resilience import elastic

    t = _moe_trainer(ShardingPlan(dp=1, pp=2, ep=4))
    for x, y in _moe_batches(2):
        t.step(x, y)
    ck = str(tmp_path / "ck")
    parallel.save_checkpoint(t, ck)

    before = elastic.elastic_stats()["replans"]
    t2 = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))
    parallel.restore_checkpoint(t2, ck)
    assert t2._t == 2
    assert elastic.elastic_stats()["replans"] == before + 1

    # same placement back in: a restore that is NOT a re-plan
    t3 = _moe_trainer(ShardingPlan(dp=1, pp=2, ep=4))
    parallel.restore_checkpoint(t3, ck)
    assert elastic.elastic_stats()["replans"] == before + 1


def test_plan_checkpoint_restores_into_planless_trainer(tmp_path):
    """Back-compat both ways: a plan-stamped checkpoint restores into a
    trainer built without a plan (plan recorded-and-ignored), and the
    pre-plan checkpoint layout keeps restoring (covered by the existing
    resilience suite, asserted here for the plan trainer)."""
    t = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))
    for x, y in _moe_batches(2):
        t.step(x, y)
    ck = str(tmp_path / "ck")
    parallel.save_checkpoint(t, ck)

    import jax
    mx.random.seed(0)
    np.random.seed(0)
    net = moe_lm_tiny()
    net.initialize(mx.init.Xavier())
    net(nd.zeros((1, 4), dtype="int32"))
    mesh = parallel.make_mesh(dp=2, devices=jax.devices()[:2])
    t2 = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-2}, mesh=mesh)
    parallel.restore_checkpoint(t2, ck)
    assert t2._t == 2 and t2.plan is None


def test_restore_pre_plan_checkpoint_without_metadata(tmp_path, monkeypatch):
    """A pre-planner checkpoint (records 'world', no 'plan') restores
    into a plan-built trainer even when orbax metadata() is unavailable:
    the retry must drop ONLY the 'plan' template key, not 'world' with
    it (regression: the joint pop un-matched the template again)."""
    import jax
    import orbax.checkpoint as ocp

    mx.random.seed(0)
    np.random.seed(0)
    net = moe_lm_tiny()
    net.initialize(mx.init.Xavier())
    net(nd.zeros((1, 4), dtype="int32"))
    mesh = parallel.make_mesh(dp=-1, devices=jax.devices())
    t = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-2}, mesh=mesh)  # planless: world, no plan
    for x, y in _moe_batches(2):
        t.step(x, y)
    ck = str(tmp_path / "ck")
    parallel.save_checkpoint(t, ck)

    t2 = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))

    def no_metadata(self, path):
        raise RuntimeError("metadata unavailable (older layout)")

    monkeypatch.setattr(ocp.PyTreeCheckpointer, "metadata", no_metadata)
    parallel.restore_checkpoint(t2, ck)
    assert t2._t == 2

    # ...and the reverse: a PLAN-stamped checkpoint restores into a
    # PLANLESS trainer without metadata — the retry must ADD the
    # statically-known plan template, not mislabel the restore as an
    # impossible reshard
    ck2 = str(tmp_path / "ck2")
    parallel.save_checkpoint(t2, ck2)
    mx.random.seed(0)
    np.random.seed(0)
    net3 = moe_lm_tiny()
    net3.initialize(mx.init.Xavier())
    net3(nd.zeros((1, 4), dtype="int32"))
    t3 = parallel.ShardedTrainer(
        net3, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-2}, mesh=mesh)
    parallel.restore_checkpoint(t3, ck2)
    assert t3._t == 2 and t3.plan is None


def test_restore_mismatch_raises_typed_plan_error(tmp_path):
    """An impossible reshard (the saved model's expert axis does not
    exist in the restoring trainer) surfaces as PlanMismatchError naming
    saved-vs-current placement — not a raw orbax/pytree failure."""
    t = _moe_trainer(ShardingPlan(dp=1, pp=2, ep=4))
    t.step(*_moe_batches(1)[0])
    ck = str(tmp_path / "ck")
    parallel.save_checkpoint(t, ck)

    mx.random.seed(0)
    np.random.seed(0)
    net2 = moe_lm_tiny(n_experts=2)  # half the experts: shapes can't land
    net2.initialize(mx.init.Xavier())
    net2(nd.zeros((1, 4), dtype="int32"))
    t2 = parallel.ShardedTrainer(
        net2, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-2}, plan=ShardingPlan(dp=4, pp=2, ep=1))
    with pytest.raises(PlanMismatchError) as ei:
        parallel.restore_checkpoint(t2, ck)
    msg = str(ei.value)
    assert "pp2" in msg and "ep4" in msg      # saved placement named
    assert "dp4" in msg and "ep1" in msg      # current placement named


# ---------------------------------------------------------------------------
# chaos kind: stall
# ---------------------------------------------------------------------------

def test_chaos_stall_blocks_until_released():
    chaos.arm("st.p", "stall", at=2, delay_ms=30000)
    assert chaos.point("st.p") is None  # call 1: not yet
    state = {"done": False}

    def blocked():
        chaos.point("st.p")  # call 2: stalls
        state["done"] = True

    th = threading.Thread(target=blocked, daemon=True)
    th.start()
    time.sleep(0.1)
    assert not state["done"]            # deterministically wedged
    chaos.release_stalls()
    th.join(5.0)
    assert state["done"]
    assert chaos.stats()["st.p"] == {"calls": 2, "fires": 1}


def test_chaos_stall_cap_and_spec_grammar():
    rules = chaos.arm_from_env("st.spec:stall(40):every=2")
    assert rules[0].kind == "stall" and rules[0].delay_ms == 40.0
    t0 = time.monotonic()
    chaos.point("st.spec")              # call 1: no fire
    chaos.point("st.spec")              # call 2: stalls, capped at 40ms
    assert 0.02 < time.monotonic() - t0 < 5.0
    with pytest.raises(ValueError):
        chaos.arm_from_env("st.bad:stall(nope)")


def test_chaos_clear_releases_parked_stalls():
    chaos.arm("st.clear", "stall", first=1, delay_ms=30000)
    th = threading.Thread(target=lambda: chaos.point("st.clear"),
                          daemon=True)
    th.start()
    time.sleep(0.05)
    chaos.clear()  # disarm + unpark: the autouse fixture's guarantee
    th.join(5.0)
    assert not th.is_alive()


# ---------------------------------------------------------------------------
# watchdog coverage: pipeline / MoE dispatch + /healthz degradation
# ---------------------------------------------------------------------------

def _pp_mesh(n=2):
    import jax
    return parallel.make_mesh(pp=n, devices=jax.devices()[:n])


def test_pipeline_stall_raises_collective_timeout(monkeypatch):
    """A hung pipeline dispatch (stalled stage) aborts with the typed
    CollectiveTimeout inside the configured deadline — never a silent
    wedge — and lands in the elastic counters + /healthz."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel.pipeline import pipeline_spmd
    from mxnet_tpu.resilience import elastic

    mesh = _pp_mesh(2)
    eye = jnp.eye(4, dtype=jnp.float32)
    params = {"w": jnp.stack([eye, 2.0 * eye])}
    x = jnp.ones((4, 4), jnp.float32)

    def stage(p, a):
        return a @ p["w"]

    # healthy path first: guarded, transparent
    monkeypatch.setenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS", "5000")
    out = pipeline_spmd(stage, params, x, mesh, n_micro=2)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones((4, 4)))
    assert elastic.health()["status"] == "ok"

    before = elastic.elastic_stats()["collective_timeouts"]
    chaos.arm("pipeline.dispatch", "stall", first=1, delay_ms=30000)
    monkeypatch.setenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS", "100")
    t0 = time.monotonic()
    with pytest.raises(CollectiveTimeout, match="pipeline.dispatch"):
        pipeline_spmd(stage, params, x, mesh, n_micro=2)
    assert time.monotonic() - t0 < 5.0  # aborted, not wedged
    assert elastic.elastic_stats()["collective_timeouts"] == before + 1
    h = elastic.health()
    assert h["status"] == "degraded" and h["reason"] == "collective_timeout"
    chaos.release_stalls()
    # the fabric moving again clears the alarm: next guarded op succeeds
    monkeypatch.setenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS", "5000")
    pipeline_spmd(stage, params, x, mesh, n_micro=2)
    assert elastic.health()["status"] == "ok"


def test_moe_dispatch_stall_raises_collective_timeout(monkeypatch):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.moe import init_moe_params, moe_ffn_sharded

    mesh = make_mesh(ep=2, devices=jax.devices()[:2])
    gate, w1, w2 = init_moe_params(0, 8, 16, 4)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)

    chaos.arm("moe.dispatch", "stall", first=1, delay_ms=30000)
    monkeypatch.setenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS", "100")
    with pytest.raises(CollectiveTimeout, match="moe.dispatch"):
        moe_ffn_sharded(x, gate, w1, w2, mesh)
    chaos.release_stalls()
    chaos.clear()
    # released + disarmed: the same dispatch completes and matches the
    # single-device routing oracle (large capacity: no drops, so local
    # vs global capacity rounding cannot diverge)
    from mxnet_tpu.parallel.moe import moe_ffn
    y, aux = moe_ffn_sharded(x, gate, w1, w2, mesh, capacity_factor=100.0)
    y_ref, aux_ref = moe_ffn(x, gate, w1, w2, capacity_factor=100.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)


def test_trainer_3d_step_dispatch_guarded(monkeypatch):
    """The fused training step of a multi-axis plan rides the same
    watchdog: a stalled dispatch raises CollectiveTimeout instead of
    wedging the job, with the trainer pre-step state intact."""
    t = _moe_trainer(ShardingPlan(dp=2, pp=2, ep=2))
    x, y = _moe_batches(1)[0]
    t.step(x, y)  # compile outside the deadline
    chaos.arm("trainer.dispatch", "stall", first=1, delay_ms=30000)
    monkeypatch.setenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS", "150")
    with pytest.raises(CollectiveTimeout):
        t.step(x, y)
    chaos.release_stalls()
    chaos.clear()
    monkeypatch.delenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS")
    assert np.isfinite(float(t.step(x, y).asnumpy()))


# ---------------------------------------------------------------------------
# supervised e2e: dp x pp x ep MoE job + host loss -> re-plan, bitwise
# (ISSUE-15 acceptance)
# ---------------------------------------------------------------------------

def _worker_env(workdir, **extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the supervisor re-spreads the devices
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
                "ELASTIC_WORKDIR": str(workdir)})
    env.update({k: str(v) for k, v in extra.items()})
    return env


@pytest.mark.slow
def test_supervised_3d_host_loss_replan_bitwise(tmp_path):
    """Worker 1 of a 2-worker dp x pp x ep MoE job dies abruptly
    (injected host_loss, exit 137). The supervisor evicts, re-forms at
    world 1 with the planner-re-spread 8-device pool; the restarted
    worker PLANS A DIFFERENT PLACEMENT (4 -> 8 devices), restores the
    rolling checkpoint across placements (counted as a re-plan), and
    its trajectory is bitwise-equal to uninterrupted restore-and-replay
    from the same snapshot at the surviving topology."""
    steps = 10
    events = tmp_path / "events.jsonl"
    env = _worker_env(tmp_path, ELASTIC_STEPS=steps, ELASTIC_CKPT_EVERY=2,
                      ELASTIC_FAIL_RANK=1, ELASTIC_FAIL_STEP=4,
                      ELASTIC_FAIL_KIND="host_loss",
                      ELASTIC_STEP_SLOW_MS=150)
    proc = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--supervise",
         "--max-restarts", "0", "--total-devices", "8",
         "--rdzv-dir", str(tmp_path / "rdzv"),
         "--event-log", str(events), "--grace-ms", "20000",
         sys.executable, WORKER],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, \
        "supervised 3D run failed:\n%s" % proc.stderr[-4000:]

    evs = [json.loads(ln) for ln in events.read_text().splitlines()]
    fail = next(e for e in evs if e["event"] == "worker_failed")
    assert fail["rank"] == 1 and fail["rc"] == 137
    assert any(e["event"] == "evicted" and e["world"] == 1 for e in evs)
    assert any(e["event"] == "run_complete" for e in evs)

    with open(tmp_path / "out" / "result_gen1_rank0.json") as f:
        resumed = json.load(f)

    # the re-formed world absorbed the planner-re-spread pool (4 -> 8
    # devices per worker) and chose a DIFFERENT placement than gen 0's
    # 4-device plan (recomputed here with the worker's exact budget rule
    # — the planner is deterministic): a genuine 3D re-plan, counted as
    # such by the restore, and the re-formed placement spans ALL of
    # dp x pp x ep
    prof = moe_lm_tiny().profile(batch=48, seq=64)  # worker geometry
    gen0_plan = plan_sharding(
        4, prof,
        hbm_bytes=int(planner.min_memory_per_device(4, prof) * 1.25)
    ).to_dict()
    assert resumed["devices"] == 8 and resumed["world"] == 1
    assert resumed["plan"]["n_devices"] == 8
    assert resumed["plan"] != gen0_plan
    assert resumed["plan"]["dp"] > 1 and resumed["plan"]["pp"] > 1 \
        and resumed["plan"]["ep"] > 1
    assert resumed["replans"] >= 1
    assert 0 < resumed["start_step"] < steps
    assert resumed["end_step"] == steps

    # reference: restore-and-replay from the same snapshot, same pool
    ref = tmp_path / "ref"
    os.makedirs(ref / "ckpt-rank0")
    shutil.copytree(tmp_path / "out" / "restored_gen1_rank0",
                    ref / "ckpt-rank0" / "resume_ckpt")
    renv = _worker_env(ref, ELASTIC_STEPS=steps, MXTPU_GENERATION=1)
    renv["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    rproc = subprocess.run([sys.executable, WORKER], env=renv,
                           capture_output=True, text=True, timeout=240)
    assert rproc.returncode == 0, rproc.stderr[-3000:]
    with open(ref / "out" / "result_gen1_rank0.json") as f:
        refres = json.load(f)
    assert refres["start_step"] == resumed["start_step"]
    assert refres["plan"] == resumed["plan"]
    assert refres["losses"] == resumed["losses"]          # bitwise
    assert refres["params_sha256"] == resumed["params_sha256"]
