"""Autograd semantics tests (model: reference tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd as ag


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with ag.record():
        y = nd.exp(nd.log(x) * 2.0)  # x^2
        z = y.sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-5)


def test_multi_variable():
    a = nd.array([2.0])
    b = nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = a * b + a
    c.backward()
    assert np.allclose(a.grad.asnumpy(), [4.0])  # b + 1
    assert np.allclose(b.grad.asnumpy(), [2.0])  # a


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3.0
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert np.allclose(x.grad.asnumpy(), 3 * 2 * x.asnumpy())


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with ag.record():
        y = x * 2
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_pause():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        with ag.pause():
            z = y * 3  # not recorded
        w = y.sum()
    w.backward()
    assert np.allclose(x.grad.asnumpy(), [2.0, 2.0])


def test_is_recording_training():
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
    with ag.record(train_mode=False):
        assert ag.is_recording()
        assert not ag.is_training()
    with ag.train_mode():
        assert ag.is_training()


def test_detach():
    x = nd.array([1.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    # dz/dx = y.detach() = 2 (no flow through y)
    assert np.allclose(x.grad.asnumpy(), [2.0])


def test_stop_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = nd.stop_gradient(x * 2) * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_functional_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x ** 3).sum()
        gx, = ag.grad(y, x)
    assert np.allclose(gx.asnumpy(), 3 * x.asnumpy() ** 2)


def test_higher_order():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = (x ** 3).sum()
        gx, = ag.grad(y, x, create_graph=True)
        z = gx.sum()
    z.backward()
    # d2y/dx2 = 6x = 12
    assert np.allclose(x.grad.asnumpy(), [12.0])


def test_mark_variables():
    x = nd.array([5.0])
    g = nd.zeros((1,))
    ag.mark_variables([x], [g])
    with ag.record():
        y = x * x
    y.backward()
    assert np.allclose(g.asnumpy(), [10.0])
    assert x.grad is g


def test_backward_through_reshape_and_reduce():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.attach_grad()
    with ag.record():
        y = x.reshape((3, 2)).transpose()
        z = (y * y).mean()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy() / 6, rtol=1e-5)


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
        z = y.sum()
    z.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_dropout_grad():
    x = nd.ones((100,))
    x.attach_grad()
    with ag.record():
        y = nd.Dropout(x, p=0.5)
        z = y.sum()
    z.backward()
    g = x.grad.asnumpy()
    # grads are 0 or 2 (1/keep_prob)
    assert set(np.unique(g)).issubset({0.0, 2.0})
