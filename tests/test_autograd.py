"""Autograd semantics tests (model: reference tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd as ag


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with ag.record():
        y = nd.exp(nd.log(x) * 2.0)  # x^2
        z = y.sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-5)


def test_multi_variable():
    a = nd.array([2.0])
    b = nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = a * b + a
    c.backward()
    assert np.allclose(a.grad.asnumpy(), [4.0])  # b + 1
    assert np.allclose(b.grad.asnumpy(), [2.0])  # a


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3.0
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert np.allclose(x.grad.asnumpy(), 3 * 2 * x.asnumpy())


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with ag.record():
        y = x * 2
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_pause():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        with ag.pause():
            z = y * 3  # not recorded
        w = y.sum()
    w.backward()
    assert np.allclose(x.grad.asnumpy(), [2.0, 2.0])


def test_is_recording_training():
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
    with ag.record(train_mode=False):
        assert ag.is_recording()
        assert not ag.is_training()
    with ag.train_mode():
        assert ag.is_training()


def test_detach():
    x = nd.array([1.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    # dz/dx = y.detach() = 2 (no flow through y)
    assert np.allclose(x.grad.asnumpy(), [2.0])


def test_stop_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = nd.stop_gradient(x * 2) * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_functional_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x ** 3).sum()
        gx, = ag.grad(y, x)
    assert np.allclose(gx.asnumpy(), 3 * x.asnumpy() ** 2)


def test_higher_order():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = (x ** 3).sum()
        gx, = ag.grad(y, x, create_graph=True)
        z = gx.sum()
    z.backward()
    # d2y/dx2 = 6x = 12
    assert np.allclose(x.grad.asnumpy(), [12.0])


def test_mark_variables():
    x = nd.array([5.0])
    g = nd.zeros((1,))
    ag.mark_variables([x], [g])
    with ag.record():
        y = x * x
    y.backward()
    assert np.allclose(g.asnumpy(), [10.0])
    assert x.grad is g


def test_backward_through_reshape_and_reduce():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.attach_grad()
    with ag.record():
        y = x.reshape((3, 2)).transpose()
        z = (y * y).mean()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy() / 6, rtol=1e-5)


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
        z = y.sum()
    z.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_dropout_grad():
    x = nd.ones((100,))
    x.attach_grad()
    with ag.record():
        y = nd.Dropout(x, p=0.5)
        z = y.sum()
    z.backward()
    g = x.grad.asnumpy()
    # grads are 0 or 2 (1/keep_prob)
    assert set(np.unique(g)).issubset({0.0, 2.0})


def test_higher_order_transcendental():
    """reference tests/python/unittest/test_higher_order_grad.py: second
    derivatives of transcendental ops match closed forms."""
    cases = [
        ("sin", lambda v: -onp_sin(v)),        # d2 sin = -sin
        ("exp", lambda v: onp_exp(v)),         # d2 exp = exp
        ("log", lambda v: -1.0 / v ** 2),      # d2 log = -1/x^2
        ("sigmoid", None),                     # checked vs finite diff
    ]
    import numpy as onp
    global onp_sin, onp_exp
    onp_sin, onp_exp = onp.sin, onp.exp
    vals = onp.array([0.3, 0.7, 1.3], dtype="float32")
    for name, d2 in cases:
        x = nd.array(vals.copy())
        x.attach_grad()
        with ag.record():
            y = getattr(nd, name)(x).sum()
            gx, = ag.grad(y, x, create_graph=True)
            z = gx.sum()
        z.backward()
        got = x.grad.asnumpy()
        if d2 is not None:
            onp.testing.assert_allclose(got, d2(vals), rtol=1e-4,
                                        atol=1e-5)
        else:
            eps = 1e-3

            def g1(v):
                s = 1 / (1 + onp.exp(-v))
                return s * (1 - s)
            fd = (g1(vals + eps) - g1(vals - eps)) / (2 * eps)
            onp.testing.assert_allclose(got, fd, rtol=1e-2, atol=1e-4)


def test_third_order_grad():
    # d3/dx3 of x^4 = 24 x
    x = nd.array([1.5])
    x.attach_grad()
    with ag.record():
        y = (x ** 4).sum()
        g1, = ag.grad(y, x, create_graph=True)
        g2, = ag.grad(g1.sum(), x, create_graph=True)
        z = g2.sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [24 * 1.5])
