"""mxnet_tpu.serving tests — bucketed engine, dynamic batcher, metrics,
HTTP server; plus the CachedOp LRU and profiler aggregate satellites.

Covers the ISSUE-1 acceptance criteria on the CPU oracle:
(a) batched throughput >= 2x sequential at concurrency 8,
(b) XLA compiles for 100 mixed-size requests bounded by the bucket ladder,
(c) bounded queue rejects with ServerBusy (no deadlock) and shutdown
    drains in-flight requests.
"""
import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.cached_op import CachedOp, cache_stats, reset_cache_stats
from mxnet_tpu.serving import (DeadlineExceeded, DynamicBatcher,
                               InferenceEngine, ModelServer, ServerBusy,
                               ServerClosed, ServingMetrics)

D_IN, D_OUT = 8, 3
_W = np.linspace(-1, 1, D_IN * D_OUT).reshape(D_IN, D_OUT).astype("float32")


def _linear(x):
    """Tiny deterministic model: (n, D_IN) -> (n, D_OUT)."""
    return nd.dot(x, nd.array(_W))


def _ref(x):
    return np.asarray(x, "float32") @ _W


# ---------------------------------------------------------------------------
# InferenceEngine: bucket padding, compile bound, chunking, warmup, load
# ---------------------------------------------------------------------------

def test_engine_bucket_padding_and_unpad():
    seen = []

    def spy(x):
        seen.append(x.shape[0])
        return _linear(x)

    eng = InferenceEngine(spy, buckets=(2, 4, 8), jit=False)
    for n in (1, 3, 4, 7, 2):
        x = np.random.randn(n, D_IN).astype("float32")
        out = eng.predict(x)
        assert out.shape == (n, D_OUT)
        np.testing.assert_allclose(out.asnumpy(), _ref(x),
                                   rtol=1e-5, atol=1e-6)
    # every executed batch was padded up to a configured bucket
    assert seen == [2, 4, 4, 8, 2]
    assert eng.stats()["buckets_seen"] == [2, 4, 8]


def test_engine_compile_bound_100_mixed_requests():
    """Acceptance (b): 100 mixed-size requests -> compiles <= #buckets."""
    buckets = (1, 2, 4, 8, 16, 32)
    eng = InferenceEngine(_linear, buckets=buckets)
    rng = np.random.default_rng(0)
    for _ in range(100):
        n = int(rng.integers(1, 33))
        x = rng.standard_normal((n, D_IN)).astype("float32")
        out = eng.predict(x)
        assert out.shape == (n, D_OUT)
        np.testing.assert_allclose(out.asnumpy(), _ref(x),
                                   rtol=1e-4, atol=1e-5)
    st = eng.stats()
    assert st["compiles"] <= len(buckets), st
    assert st["hits"] + st["misses"] >= 100


def test_engine_oversize_batch_chunks():
    eng = InferenceEngine(_linear, buckets=(2, 4))
    x = np.random.randn(11, D_IN).astype("float32")  # > max bucket (4)
    out = eng.predict(x)
    assert out.shape == (11, D_OUT)
    np.testing.assert_allclose(out.asnumpy(), _ref(x), rtol=1e-5, atol=1e-6)
    assert eng.stats()["compiles"] <= 2


def test_engine_warmup_precompiles_all_buckets():
    eng = InferenceEngine(_linear, buckets=(1, 2, 4))
    eng.warmup(np.zeros(D_IN, "float32")[None])
    st = eng.stats()
    assert st["buckets_seen"] == [1, 2, 4]
    compiles_after_warmup = st["compiles"]
    for n in (1, 2, 3, 4):
        eng.predict(np.random.randn(n, D_IN).astype("float32"))
    # no new compiles after warmup
    assert eng.stats()["compiles"] == compiles_after_warmup


def test_engine_load_from_export_artifacts(tmp_path):
    net = mx.gluon.nn.Dense(D_OUT, in_units=D_IN)
    net.initialize()
    x = nd.array(np.random.randn(2, D_IN).astype("float32"))
    ref = net(x).asnumpy()
    path = str(tmp_path / "model")
    net.export(path)
    eng = InferenceEngine.load(path, input_names=("data",), buckets=(2, 4))
    out = eng.predict(x)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# DynamicBatcher: coalescing, deadlines, backpressure, drain
# ---------------------------------------------------------------------------

def test_batcher_coalesces_concurrent_requests():
    calls = []

    def spy(x):
        calls.append(x.shape[0])
        return x * 2.0

    with DynamicBatcher(spy, max_batch_size=8, max_latency_ms=50) as b:
        futs = [b.submit(np.full((3,), i, "float32")) for i in range(8)]
        rows = [f.result(timeout=5) for f in futs]
    for i, row in enumerate(rows):
        np.testing.assert_allclose(row, np.full((3,), 2.0 * i))
    # 8 requests coalesced into far fewer executions
    assert len(calls) < 8
    assert sum(calls) == 8


def test_batcher_with_engine_correct_row_mapping():
    m = ServingMetrics()
    eng = InferenceEngine(_linear, buckets=(1, 2, 4, 8, 16), metrics=m)
    with DynamicBatcher(eng, max_batch_size=16, max_latency_ms=20,
                        metrics=m) as b:
        xs = [np.random.randn(D_IN).astype("float32") for _ in range(12)]
        futs = [b.submit(x) for x in xs]
        for x, f in zip(xs, futs):
            np.testing.assert_allclose(f.result(timeout=10), _ref(x[None])[0],
                                       rtol=1e-4, atol=1e-5)
    snap = m.snapshot()
    assert snap["requests"] == 12 and snap["ok"] == 12
    assert snap["batches"] >= 1
    assert 0.0 < snap["batch_occupancy"] <= 1.0


def test_batcher_mixed_signatures_split_into_batches():
    def echo_sum(x):
        return nd.sum(nd.array(x), axis=tuple(range(1, x.ndim)))

    with DynamicBatcher(echo_sum, max_batch_size=8,
                        max_latency_ms=30) as b:
        fa = [b.submit(np.ones((2,), "float32") * i) for i in range(3)]
        fb = [b.submit(np.ones((5,), "float32") * i) for i in range(3)]
        for i, f in enumerate(fa):
            np.testing.assert_allclose(f.result(timeout=5), 2.0 * i)
        for i, f in enumerate(fb):
            np.testing.assert_allclose(f.result(timeout=5), 5.0 * i)


def test_batcher_deadline_expiry():
    gate = threading.Event()
    entered = threading.Event()

    def slow(x):
        entered.set()
        assert gate.wait(10)
        return x

    m = ServingMetrics()
    b = DynamicBatcher(slow, max_batch_size=1, max_latency_ms=0, metrics=m)
    try:
        f1 = b.submit(np.zeros(2, "float32"))           # occupies the worker
        assert entered.wait(5)
        f2 = b.submit(np.zeros(2, "float32"), timeout_ms=30)
        time.sleep(0.15)                                 # f2 expires queued
        gate.set()
        assert f1.result(timeout=5) is not None
        with pytest.raises(DeadlineExceeded):
            f2.result(timeout=5)
        assert m.snapshot()["expired"] == 1
    finally:
        gate.set()
        b.close()


def test_batcher_server_busy_backpressure_and_recovery():
    """Acceptance (c): saturated bounded queue rejects with ServerBusy
    instead of deadlocking, and keeps serving once drained."""
    gate = threading.Event()
    entered = threading.Event()

    def slow(x):
        entered.set()
        assert gate.wait(10)
        return x + 1.0

    m = ServingMetrics()
    b = DynamicBatcher(slow, max_batch_size=1, max_latency_ms=0,
                       max_queue_size=2, metrics=m)
    try:
        f0 = b.submit(np.zeros(1, "float32"))      # in flight
        assert entered.wait(5)
        # fill the bounded queue exactly
        deadline = time.monotonic() + 5
        queued = []
        while len(queued) < 2 and time.monotonic() < deadline:
            try:
                queued.append(b.submit(np.zeros(1, "float32")))
            except ServerBusy:
                time.sleep(0.01)
        assert len(queued) == 2
        with pytest.raises(ServerBusy):
            for _ in range(50):  # full queue must shed, never block
                b.submit(np.zeros(1, "float32"))
        assert m.snapshot()["rejected"] >= 1
        gate.set()                                  # recover
        assert f0.result(timeout=5) is not None
        for f in queued:
            np.testing.assert_allclose(f.result(timeout=5), [1.0])
        # after draining, new submissions are accepted again
        np.testing.assert_allclose(b.predict(np.zeros(1, "float32")), [1.0])
    finally:
        gate.set()
        b.close()


def test_batcher_close_drains_in_flight():
    """Acceptance (c): shutdown completes everything already queued."""
    def slowish(x):
        time.sleep(0.02)
        return x * 3.0

    b = DynamicBatcher(slowish, max_batch_size=2, max_latency_ms=1)
    futs = [b.submit(np.full((1,), i, "float32")) for i in range(6)]
    b.close(drain=True)
    for i, f in enumerate(futs):
        assert f.done()
        np.testing.assert_allclose(f.result(), [3.0 * i])
    with pytest.raises(ServerClosed):
        b.submit(np.zeros(1, "float32"))


def test_batcher_close_no_drain_fails_pending():
    gate = threading.Event()
    entered = threading.Event()

    def slow(x):
        entered.set()
        assert gate.wait(10)
        return x

    b = DynamicBatcher(slow, max_batch_size=1, max_latency_ms=0)
    b.submit(np.zeros(1, "float32"))
    assert entered.wait(5)
    pending = b.submit(np.zeros(1, "float32"))
    gate.set()
    b.close(drain=False)
    with pytest.raises(ServerClosed):
        pending.result(timeout=5)


def test_batcher_model_error_propagates():
    def boom(x):
        raise ValueError("bad weights")

    with DynamicBatcher(boom, max_batch_size=4, max_latency_ms=1) as b:
        f = b.submit(np.zeros(2, "float32"))
        with pytest.raises(ValueError, match="bad weights"):
            f.result(timeout=5)


def test_batched_throughput_2x_over_sequential():
    """Acceptance (a): DynamicBatcher at concurrency 8 >= 2x sequential
    single-request throughput (per-dispatch overhead amortizes across the
    coalesced batch). Requests are kept 8-deep via waves of futures; both
    paths take the best of 3 trials to shed CI scheduler noise (this
    oracle host has 2 cores)."""
    W = np.random.randn(256, 256).astype("float32")
    Wn = nd.array(W)

    def model(x):
        return nd.dot(x, Wn)

    n_requests = 96
    eng = InferenceEngine(model, buckets=(1, 2, 4, 8))
    eng.warmup(np.zeros((1, 256), "float32"))
    x1 = np.random.randn(1, 256).astype("float32")
    sample = x1[0]

    def run_sequential():
        t0 = time.perf_counter()
        for _ in range(n_requests):
            eng.predict(x1)[0].asnumpy()   # sync each request, like a client
        return time.perf_counter() - t0

    def run_batched():
        with DynamicBatcher(eng, max_batch_size=8, max_latency_ms=20) as b:
            b.predict(sample)              # prime the worker path
            t0 = time.perf_counter()
            for _ in range(n_requests // 8):
                futs = [b.submit(sample) for _ in range(8)]  # 8 in flight
                for f in futs:
                    f.result(timeout=30)
            return time.perf_counter() - t0

    seq_s = min(run_sequential() for _ in range(3))
    bat_s = min(run_batched() for _ in range(3))
    speedup = seq_s / bat_s
    assert speedup >= 2.0, (
        "batched throughput only %.2fx sequential (seq %.3fs, batched %.3fs)"
        % (speedup, seq_s, bat_s))


# ---------------------------------------------------------------------------
# Metrics + profiler satellites
# ---------------------------------------------------------------------------

def test_metrics_percentiles_and_qps():
    m = ServingMetrics(window=64)
    for lat in (0.010, 0.020, 0.030, 0.040):
        m.record_request(lat)
    p = m.percentiles()
    assert p["p50"] == pytest.approx(20.0)
    assert p["p99"] == pytest.approx(40.0)
    snap = m.snapshot()
    assert snap["requests"] == 4 and snap["qps"] > 0
    assert snap["latency_ms"]["mean"] == pytest.approx(25.0)


def test_metrics_profiler_aggregate_integration():
    from mxnet_tpu import profiler
    m = ServingMetrics(name="srv_test")
    m.record_request(0.005)
    m.record_batch(4, 8)
    m.bind_profiler()
    try:
        stats = profiler.get_aggregate_stats()
        assert stats["srv_test.requests"]["calls"] == 1
        assert stats["srv_test.requests"]["total_ms"] == pytest.approx(5.0)
        assert stats["srv_test.batches"]["calls"] == 1
        assert "srv_test.requests" in profiler.dumps()
    finally:
        m.unbind_profiler()
    assert "srv_test.requests" not in profiler.get_aggregate_stats()


def test_profiler_get_aggregate_stats_matches_dumps():
    from mxnet_tpu import profiler
    with profiler.Domain("d").new_task("agg_probe"):
        time.sleep(0.002)
    stats = profiler.get_aggregate_stats()
    assert stats["agg_probe"]["calls"] >= 1
    assert stats["agg_probe"]["total_ms"] > 0
    assert "agg_probe" in profiler.dumps()


# ---------------------------------------------------------------------------
# CachedOp LRU satellite
# ---------------------------------------------------------------------------

def test_cached_op_lru_bound_and_counters():
    op = CachedOp(lambda x: x * 2.0, capacity=2)
    for n in (1, 2, 3):
        op(nd.array(np.ones((n, 2), "float32")))
    st = op.cache_stats()
    assert st["size"] == 2 and st["capacity"] == 2
    assert st["misses"] == 3 and st["evictions"] == 1 and st["hits"] == 0
    # signature 1 was evicted (LRU) -> recompiles; signature 3 still hits
    op(nd.array(np.ones((3, 2), "float32")))
    assert op.cache_stats()["hits"] == 1
    op(nd.array(np.ones((1, 2), "float32")))
    assert op.cache_stats()["misses"] == 4


def test_cached_op_global_cache_stats():
    reset_cache_stats()
    base = cache_stats()
    assert base == {"hits": 0, "misses": 0, "evictions": 0}
    op = CachedOp(lambda x: x + 1.0)
    op(nd.array(np.ones((2, 2), "float32")))
    op(nd.array(np.ones((2, 2), "float32")))
    st = cache_stats()
    assert st["misses"] >= 1 and st["hits"] >= 1


def test_cached_op_capacity_env_knob(monkeypatch):
    monkeypatch.setenv("MXNET_CACHED_OP_CAPACITY", "3")
    op = CachedOp(lambda x: x)
    assert op._capacity == 3
    monkeypatch.delenv("MXNET_CACHED_OP_CAPACITY")
    assert CachedOp(lambda x: x)._capacity == 64  # documented default


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------

def _post_json(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_model_server_endpoints():
    with ModelServer(_linear, port=0, buckets=(1, 2, 4),
                     max_latency_ms=2) as srv:
        url = srv.url
        code, body = _get_json(url + "/healthz")
        assert code == 200 and body["status"] == "ok"

        x = np.random.randn(D_IN).astype("float32")
        code, body = _post_json(url + "/predict", {"data": x.tolist()})
        assert code == 200
        np.testing.assert_allclose(body["output"], _ref(x[None])[0],
                                   rtol=1e-4, atol=1e-5)

        code, body = _get_json(url + "/metrics")
        assert code == 200
        assert body["requests"] >= 1 and body["ok"] >= 1
        assert body["executor_cache"]["compiles"] >= 1

        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_json(url + "/predict", {"nope": 1})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(url + "/bogus")
        assert ei.value.code == 404


def test_model_server_reports_model_error_500():
    def boom(x):
        raise RuntimeError("exploded")

    with ModelServer(boom, port=0, jit=False, max_latency_ms=1) as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_json(srv.url + "/predict", {"data": [1.0, 2.0]})
        assert ei.value.code == 500


# ---------------------------------------------------------------------------
# End-to-end (slow): concurrent HTTP traffic through a real model
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_e2e_bert_concurrent_http():
    from mxnet_tpu.models.bert import bert_tiny
    V, T = 1000, 32
    mx.random.seed(0)
    net = bert_tiny(vocab_size=V, max_length=T)
    net.initialize(mx.init.Xavier())
    eng = InferenceEngine(net, buckets=(1, 2, 4, 8))
    with ModelServer(eng, port=0, max_batch_size=8,
                     max_latency_ms=15) as srv:
        rng = np.random.default_rng(0)

        def client(k):
            out = []
            for _ in range(4):
                tokens = rng.integers(0, V, (T,)).astype("float32")
                segments = np.zeros((T,), "float32")
                code, body = _post_json(
                    srv.url + "/predict",
                    {"inputs": [tokens.tolist(), segments.tolist()]},
                    timeout=120)
                assert code == 200
                out.append(body["outputs"])
            return out

        with ThreadPoolExecutor(max_workers=8) as pool:
            results = list(pool.map(client, range(8)))
        for client_out in results:
            for outs in client_out:
                seq, pooled, mlm, nsp = outs
                assert np.asarray(seq).shape == (T, 128)
                assert np.asarray(pooled).shape == (128,)
                assert np.asarray(mlm).shape == (T, V)
                assert np.asarray(nsp).shape == (2,)
                assert np.isfinite(np.asarray(nsp)).all()
        code, m = _get_json(srv.url + "/metrics")
        assert m["requests"] == 32 and m["errors"] == 0
        assert m["executor_cache"]["compiles"] <= 4
        assert m["avg_batch_size"] > 1.0  # traffic actually coalesced
