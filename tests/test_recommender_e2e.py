"""Matrix-factorization recommender slice — mirrors reference
`example/recommenders`: embedding factors recover a low-rank matrix."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "example", "recommenders"))

from matrix_fact import train  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def test_mf_recovers_low_rank_matrix():
    net, ratings, first, last = train(steps=150, log=lambda *a: None)
    assert last < first * 0.05
    nu, ni = ratings.shape
    uu, ii = np.meshgrid(np.arange(nu), np.arange(ni), indexing="ij")
    pred = net(mx.nd.array(uu.ravel().astype("float32")),
               mx.nd.array(ii.ravel().astype("float32"))).asnumpy()
    rmse = float(np.sqrt(np.mean((pred - ratings.ravel()) ** 2)))
    assert rmse < 0.15 * ratings.std(), "RMSE %.4f vs std %.3f" % (
        rmse, ratings.std())
