"""INDEPENDENT writer for a reference-convention model export fixture.

Deliberately imports NOTHING from mxnet_tpu: the bytes below are written
straight from the documented reference formats, so the fixture proves the
framework's readers parse the reference convention — not merely their own
writer's output (VERDICT r3 missing item 6; conventions from
reference `python/mxnet/gluon/block.py:1077` export, `src/ndarray/
ndarray.cc:1591` NDArray::Save, nnvm json graph).

Model: data -> FullyConnected(num_hidden=4) -> Activation(relu)
Weights are deterministic so the loader test can compute the expected
forward in plain numpy.

Run: python tests/data/make_reference_fixture.py  (writes into tests/data/)
"""
import json
import os
import struct

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def fixture_params():
    rng = np.random.RandomState(42)
    w = rng.randn(4, 6).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    return w, b


def write_symbol_json(path):
    # reference nnvm convention: attrs are PLAIN strings, inputs/heads are
    # 3-element [node, out_index, version] entries, extra bookkeeping keys
    # (node_row_ptr, top-level attrs) present
    graph = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc0_weight",
             "attrs": {"__lr_mult__": "1.0"}, "inputs": []},
            {"op": "null", "name": "fc0_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc0",
             "attrs": {"num_hidden": "4", "no_bias": "False",
                       "flatten": "True"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "relu0",
             "attrs": {"act_type": "relu"},
             "inputs": [[3, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "node_row_ptr": [0, 1, 2, 3, 4, 5],
        "heads": [[4, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10600]},
    }
    with open(path, "w") as fh:
        json.dump(graph, fh, indent=2)


def write_params(path):
    """reference binary container, written with struct only:
    uint64 magic=0x112, uint64 reserved, uint64 count, V2 records
    (uint32 0xF993FAC9, int32 stype=0, int32 ndim, int64 dims,
    int32 dev_type=1, int32 dev_id=0, int32 type_flag=0, raw bytes),
    then uint64 name-count and (uint64 len, bytes) names with the
    gluon export 'arg:'/'aux:' prefixes."""
    w, b = fixture_params()
    arrays = [("arg:fc0_weight", w), ("arg:fc0_bias", b)]
    with open(path, "wb") as fh:
        fh.write(struct.pack("<QQQ", 0x112, 0, len(arrays)))
        for _, a in arrays:
            fh.write(struct.pack("<I", 0xF993FAC9))
            fh.write(struct.pack("<i", 0))
            fh.write(struct.pack("<i", a.ndim))
            fh.write(struct.pack("<%dq" % a.ndim, *a.shape))
            fh.write(struct.pack("<ii", 1, 0))
            fh.write(struct.pack("<i", 0))  # float32
            fh.write(a.tobytes())
        fh.write(struct.pack("<Q", len(arrays)))
        for name, _ in arrays:
            raw = name.encode()
            fh.write(struct.pack("<Q", len(raw)))
            fh.write(raw)


def main():
    write_symbol_json(os.path.join(HERE, "ref_export-symbol.json"))
    write_params(os.path.join(HERE, "ref_export-0000.params"))
    print("wrote reference-convention fixture into", HERE)


if __name__ == "__main__":
    main()
