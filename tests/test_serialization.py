"""MXNet-binary NDArray container round-trip + byte-format tests
(reference src/ndarray/ndarray.cc:1591-1852)."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import serialization as ser


def test_save_load_dict_roundtrip(tmp_path):
    path = str(tmp_path / "weights.params")
    data = {
        "conv0_weight": mx.nd.array(np.random.randn(4, 3, 3, 3).astype(np.float32)),
        "fc0_bias": mx.nd.array(np.arange(10, dtype=np.float32)),
        "idx": mx.nd.array(np.array([1, 2, 3], dtype=np.int32)),
    }
    mx.nd.save(path, data)
    out = mx.nd.load(path)
    assert set(out.keys()) == set(data.keys())
    for k in data:
        np.testing.assert_array_equal(out[k].asnumpy(), data[k].asnumpy())
        assert out[k].dtype == data[k].dtype


def test_save_load_list_roundtrip(tmp_path):
    path = str(tmp_path / "arrs.nd")
    data = [mx.nd.array(np.random.randn(2, 3).astype(np.float32)),
            mx.nd.array(np.array(7.0, dtype=np.float64))]
    mx.nd.save(path, data)
    out = mx.nd.load(path)
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_array_equal(out[0].asnumpy(), data[0].asnumpy())
    np.testing.assert_array_equal(out[1].asnumpy(), data[1].asnumpy())


def test_binary_layout_golden(tmp_path):
    """Byte-for-byte check of the container framing against the reference
    format spec (kMXAPINDArrayListMagic / NDARRAY_V2_MAGIC / TShape int64)."""
    path = str(tmp_path / "g.params")
    arr = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
    ser.save_ndarrays(path, [arr], ["w"])
    raw = open(path, "rb").read()
    expect = struct.pack("<QQ", 0x112, 0)          # header, reserved
    expect += struct.pack("<Q", 1)                 # ndarray count
    expect += struct.pack("<I", 0xF993FAC9)        # NDARRAY_V2_MAGIC
    expect += struct.pack("<i", 0)                 # kDefaultStorage
    expect += struct.pack("<i", 2) + struct.pack("<2q", 2, 2)  # TShape
    expect += struct.pack("<ii", 1, 0)             # Context::CPU
    expect += struct.pack("<i", 0)                 # kFloat32
    expect += arr.tobytes()
    expect += struct.pack("<Q", 1)                 # name count
    expect += struct.pack("<Q", 1) + b"w"
    assert raw == expect


def test_load_reference_written_v1_and_legacy(tmp_path):
    """Files using the older per-array magics still load (ndarray.cc:1683 LegacyLoad)."""
    path = str(tmp_path / "old.nd")
    a1 = np.arange(6, dtype=np.float32).reshape(2, 3)
    a2 = np.arange(4, dtype=np.int64)
    with open(path, "wb") as fo:
        fo.write(struct.pack("<QQ", 0x112, 0))
        fo.write(struct.pack("<Q", 2))
        # V1 record: int64 dims, no stype field
        fo.write(struct.pack("<I", 0xF993FAC8))
        fo.write(struct.pack("<i", 2) + struct.pack("<2q", 2, 3))
        fo.write(struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a1.tobytes())
        # pre-V1 record: magic == ndim, uint32 dims
        fo.write(struct.pack("<I", 1) + struct.pack("<I", 4))
        fo.write(struct.pack("<ii", 1, 0) + struct.pack("<i", 6) + a2.tobytes())
        fo.write(struct.pack("<Q", 0))
    out = mx.nd.load(path)
    assert isinstance(out, list)
    np.testing.assert_array_equal(out[0].asnumpy(), a1)
    np.testing.assert_array_equal(out[1].asnumpy(), a2)


def test_v3_unknown_shape_none_sentinel(tmp_path):
    """V3 np-shape record with ndim=-1 is the reference's none sentinel
    (ndarray.cc:1751): loader must yield a placeholder, not crash, and the
    record carries no ctx/dtype/data fields."""
    path = str(tmp_path / "v3.nd")
    a = np.float32([5.0])
    with open(path, "wb") as fo:
        fo.write(struct.pack("<QQ", 0x112, 0) + struct.pack("<Q", 2))
        fo.write(struct.pack("<I", 0xF993FACA) + struct.pack("<i", 0))
        fo.write(struct.pack("<i", -1))  # unknown shape, record ends here
        fo.write(struct.pack("<I", 0xF993FACA) + struct.pack("<i", 0))
        fo.write(struct.pack("<i", 1) + struct.pack("<q", 1))
        fo.write(struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a.tobytes())
        fo.write(struct.pack("<Q", 0))
    out = mx.nd.load(path)
    assert len(out) == 2
    np.testing.assert_array_equal(out[1].asnumpy(), a)


def test_corrupt_ndim_raises_format_error(tmp_path):
    path = str(tmp_path / "bad.nd")
    with open(path, "wb") as fo:
        fo.write(struct.pack("<QQ", 0x112, 0) + struct.pack("<Q", 1))
        fo.write(struct.pack("<I", 0xF993FAC9) + struct.pack("<i", 0))
        fo.write(struct.pack("<i", -7))
    with pytest.raises(ValueError, match="invalid NDArray file format"):
        mx.nd.load(path)


def test_gpu_context_loads_to_host(tmp_path):
    """Reference files saved from GPU record ctx gpu(0); loader ignores ctx."""
    path = str(tmp_path / "gpu.nd")
    a = np.float32([1, 2])
    with open(path, "wb") as fo:
        fo.write(struct.pack("<QQ", 0x112, 0) + struct.pack("<Q", 1))
        fo.write(struct.pack("<I", 0xF993FAC9) + struct.pack("<i", 0))
        fo.write(struct.pack("<i", 1) + struct.pack("<q", 2))
        fo.write(struct.pack("<ii", 2, 0))  # gpu(0)
        fo.write(struct.pack("<i", 0) + a.tobytes())
        fo.write(struct.pack("<Q", 0))
    out = mx.nd.load(path)
    np.testing.assert_array_equal(out[0].asnumpy(), a)


def test_bfloat16_saved_as_float32(tmp_path):
    path = str(tmp_path / "bf16.params")
    x = mx.nd.array(np.float32([1.5, -2.25])).astype("bfloat16")
    mx.nd.save(path, {"x": x})
    out = mx.nd.load(path)
    assert out["x"].dtype == np.float32
    np.testing.assert_array_equal(out["x"].asnumpy(), np.float32([1.5, -2.25]))


def test_float64_dtype_preserved(tmp_path):
    path = str(tmp_path / "f64.nd")
    a = mx.nd.array(np.array([1.0, 2.5], dtype=np.float64), dtype=np.float64)
    mx.nd.save(path, [a])
    out = mx.nd.load(path)
    assert out[0].dtype == np.float64
    np.testing.assert_array_equal(out[0].asnumpy(), np.float64([1.0, 2.5]))


def test_zero_dim_shape_preserved(tmp_path):
    """0-d arrays round-trip as 0-d (written as V3 records — a V2 ndim==0
    record is the none-sentinel)."""
    path = str(tmp_path / "s.nd")
    mx.nd.save(path, [mx.nd.array(np.array(7.0)), mx.nd.ones((2,))])
    out = mx.nd.load(path)
    assert out[0].shape == ()
    assert float(out[0].asnumpy()) == 7.0
    np.testing.assert_array_equal(out[1].asnumpy(), np.ones(2, np.float32))


def test_legacy_npz_still_loads(tmp_path):
    path = str(tmp_path / "old.npz")
    np.savez(path, w=np.float32([1, 2, 3]))
    out = mx.nd.load(str(path))
    np.testing.assert_array_equal(out["w"].asnumpy(), np.float32([1, 2, 3]))


def test_gluon_save_load_parameters_binary(tmp_path):
    path = str(tmp_path / "net.params")
    net = mx.gluon.nn.Dense(4, in_units=3)
    net.initialize()
    net(mx.nd.ones((1, 3)))
    net.save_parameters(path)
    assert ser.is_mxnet_binary(path)
    net2 = mx.gluon.nn.Dense(4, in_units=3)
    net2.load_parameters(path)
    np.testing.assert_allclose(
        net2(mx.nd.ones((1, 3))).asnumpy(),
        net(mx.nd.ones((1, 3))).asnumpy(), rtol=1e-6)
