"""Round-5 C ABI surface tests (ctypes in-process): binding-codegen
introspection, cached ops, monitor/updater callbacks, kvstore pushpull,
Ex/64 aliases, profiler tail. Reference names: c_api.h:1076-1120, :2205,
:1280."""
import ctypes
import json
import pathlib
import subprocess

import numpy as onp
import pytest

from _capi_testlib import LIB, built

pytestmark = pytest.mark.skipif(not built(),
                                reason="libmxtpu_c.so not built")

c = ctypes


@pytest.fixture(scope="module")
def lib():
    L = ctypes.CDLL(str(LIB))
    L.MXGetLastError.restype = c.c_char_p
    assert L.MXTpuInit(None) == 0, L.MXGetLastError()
    return L


def _arr(lib, np_arr):
    np_arr = onp.ascontiguousarray(np_arr, onp.float32)
    shape = (c.c_int64 * np_arr.ndim)(*np_arr.shape)
    h = c.c_void_p()
    assert lib.MXNDArrayCreate(shape, np_arr.ndim, b"float32",
                               c.byref(h)) == 0
    assert lib.MXNDArraySyncCopyFromCPU(
        h, np_arr.ctypes.data_as(c.POINTER(c.c_float)),
        c.c_int64(np_arr.size)) == 0
    return h


def _to_np(lib, h, shape):
    out = onp.zeros(shape, onp.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(c.POINTER(c.c_float)),
        c.c_int64(out.size)) == 0
    return out


def test_atomic_symbol_introspection(lib):
    n = c.c_int()
    creators = c.POINTER(c.c_void_p)()
    assert lib.MXSymbolListAtomicSymbolCreators(c.byref(n),
                                                c.byref(creators)) == 0
    assert n.value > 400, n.value
    # find Convolution and introspect it
    name = c.c_char_p()
    found = None
    for i in range(n.value):
        # creators[i] is a python int: re-wrap as c_void_p or ctypes
        # passes a truncated 32-bit int
        assert lib.MXSymbolGetAtomicSymbolName(c.c_void_p(creators[i]),
                                               c.byref(name)) == 0
        if name.value == b"Convolution":
            found = c.c_void_p(creators[i])
            break
    assert found is not None
    desc = c.c_char_p()
    num_args = c.c_int()
    arg_names = c.POINTER(c.c_char_p)()
    arg_types = c.POINTER(c.c_char_p)()
    arg_descs = c.POINTER(c.c_char_p)()
    kv = c.c_char_p()
    ret = c.c_char_p()
    assert lib.MXSymbolGetAtomicSymbolInfo(
        found, c.byref(name), c.byref(desc), c.byref(num_args),
        c.byref(arg_names), c.byref(arg_types), c.byref(arg_descs),
        c.byref(kv), c.byref(ret)) == 0
    names = [arg_names[i].decode() for i in range(num_args.value)]
    types = [arg_types[i].decode() for i in range(num_args.value)]
    assert names[0] == "data" and types[0] == "NDArray-or-Symbol"
    assert "weight" in names
    assert b"conv" in desc.value.lower() or desc.value != b""


def test_cached_op_invoke(lib):
    # symbol: y = relu(data) * 2
    data = c.c_void_p()
    assert lib.MXSymbolCreateVariable(b"data", c.byref(data)) == 0
    relu = c.c_void_p()
    assert lib.MXSymbolCreateAtomicSymbol(b"relu", 0, None, None,
                                          c.byref(relu)) == 0
    ins = (c.c_void_p * 1)(data)
    keys = (c.c_char_p * 1)(None)
    assert lib.MXSymbolCompose(relu, b"r", 1, keys, ins) == 0
    op = c.c_void_p()
    assert lib.MXCreateCachedOp(relu, c.byref(op)) == 0, \
        lib.MXGetLastError()
    x = onp.array([[-1.0, 2.0], [3.0, -4.0]], onp.float32)
    hx = _arr(lib, x)
    inputs = (c.c_void_p * 1)(hx)
    n_out = c.c_int()
    outs = c.POINTER(c.c_void_p)()
    assert lib.MXInvokeCachedOp(op, 1, inputs, c.byref(n_out),
                                c.byref(outs)) == 0, lib.MXGetLastError()
    assert n_out.value == 1
    got = _to_np(lib, c.c_void_p(outs[0]), x.shape)
    onp.testing.assert_allclose(got, onp.maximum(x, 0))
    assert lib.MXFreeCachedOp(op) == 0


def test_executor_monitor_callback(lib):
    # net: relu(fc(data)); monitor must fire with intermediate outputs
    data = c.c_void_p()
    assert lib.MXSymbolCreateVariable(b"data", c.byref(data)) == 0
    fc = c.c_void_p()
    k = (c.c_char_p * 1)(b"num_hidden")
    v = (c.c_char_p * 1)(b"3")
    assert lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", 1, k, v,
                                          c.byref(fc)) == 0
    ins = (c.c_void_p * 1)(data)
    nk = (c.c_char_p * 1)(None)
    assert lib.MXSymbolCompose(fc, b"fc", 1, nk, ins) == 0
    relu = c.c_void_p()
    assert lib.MXSymbolCreateAtomicSymbol(b"relu", 0, None, None,
                                          c.byref(relu)) == 0
    ins2 = (c.c_void_p * 1)(fc)
    assert lib.MXSymbolCompose(relu, b"r", 1, nk, ins2) == 0

    ex = c.c_void_p()
    keys = (c.c_char_p * 1)(b"data")
    ndims = (c.c_int * 1)(2)
    shape = (c.c_int64 * 2)(2, 4)
    assert lib.MXExecutorSimpleBindEx(relu, b"cpu", b"null", 1, keys,
                                      ndims, shape, c.byref(ex)) == 0, \
        lib.MXGetLastError()

    seen = []
    CB = c.CFUNCTYPE(None, c.c_char_p, c.c_void_p, c.c_void_p)

    def cb(name, arr_handle, _data):
        seen.append(name.decode())

    cb_keep = CB(cb)
    assert lib.MXExecutorSetMonitorCallbackEX(ex, cb_keep, None, 1) == 0, \
        lib.MXGetLastError()
    assert lib.MXExecutorForward(ex, 0) == 0, lib.MXGetLastError()
    assert seen, "monitor callback never fired"


def test_kvstore_pushpull_and_roles(lib):
    out = c.c_int()
    assert lib.MXKVStoreIsWorkerNode(c.byref(out)) == 0 and out.value == 1
    assert lib.MXKVStoreIsServerNode(c.byref(out)) == 0 and out.value == 0
    kv = c.c_void_p()
    assert lib.MXKVStoreCreate(b"local", c.byref(kv)) == 0
    val = _arr(lib, onp.ones((2, 2), onp.float32))
    keys = (c.c_char_p * 1)(b"w")
    vals = (c.c_void_p * 1)(val)
    assert lib.MXKVStoreInitEx(kv, 1, keys, vals) == 0
    push = _arr(lib, 3 * onp.ones((2, 2), onp.float32))
    outh = _arr(lib, onp.zeros((2, 2), onp.float32))
    ins = (c.c_void_p * 1)(push)
    outs = (c.c_void_p * 1)(outh)
    assert lib.MXKVStorePushPull(kv, 1, keys, ins, outs, 0) == 0, \
        lib.MXGetLastError()
    got = _to_np(lib, outh, (2, 2))
    onp.testing.assert_allclose(got, 3 * onp.ones((2, 2)))


def test_kvstore_updater_callback(lib):
    kv = c.c_void_p()
    assert lib.MXKVStoreCreate(b"local", c.byref(kv)) == 0
    calls = []
    CB = c.CFUNCTYPE(None, c.c_int, c.c_void_p, c.c_void_p, c.c_void_p)

    def updater(key, recv, local, _data):
        calls.append(key)

    keep = CB(updater)
    assert lib.MXKVStoreSetUpdater(kv, keep, None) == 0, \
        lib.MXGetLastError()
    val = _arr(lib, onp.ones((2,), onp.float32))
    keys = (c.c_char_p * 1)(b"3")
    vals = (c.c_void_p * 1)(val)
    assert lib.MXKVStoreInit(kv, 1, keys, vals) == 0
    assert lib.MXKVStorePush(kv, 1, keys, vals, 0) == 0
    assert calls, "custom updater never invoked"
    assert calls[0] == 3


def test_shape_and_invoke_aliases(lib):
    x = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    h = _arr(lib, x)
    ndim = c.c_int()
    dims = (c.c_int64 * 8)()
    assert lib.MXNDArrayGetShapeEx64(h, c.byref(ndim), dims, 8) == 0
    assert list(dims[:ndim.value]) == [2, 3]
    # imperative Ex with stypes
    outs = (c.c_void_p * 4)()
    n_out = c.c_int(4)
    stypes = c.POINTER(c.c_int)()
    ins = (c.c_void_p * 1)(h)
    assert lib.MXImperativeInvokeEx(b"relu", ins, 1, b"{}", outs,
                                    c.byref(n_out), c.byref(stypes)) == 0
    assert n_out.value == 1 and stypes[0] == 0
    # raw-bytes round trip
    size = c.c_size_t()
    buf = c.POINTER(c.c_char)()
    assert lib.MXNDArraySaveRawBytes(h, c.byref(size), c.byref(buf)) == 0
    raw = c.string_at(buf, size.value)
    h2 = c.c_void_p()
    assert lib.MXNDArrayLoadFromRawBytes(raw, len(raw), c.byref(h2)) == 0
    onp.testing.assert_allclose(_to_np(lib, h2, (2, 3)), x)


def test_autograd_backward_ex_variables(lib):
    x = _arr(lib, onp.array([2.0, 3.0], onp.float32))
    g = _arr(lib, onp.zeros(2, onp.float32))
    handles = (c.c_void_p * 1)(x)
    grads = (c.c_void_p * 1)(g)
    reqs = (c.c_int * 1)(1)
    assert lib.MXAutogradMarkVariables(1, handles, reqs, grads) == 0
    prev = c.c_int()
    assert lib.MXAutogradSetIsRecording(1, c.byref(prev)) == 0
    outs = (c.c_void_p * 4)()
    n_out = c.c_int(4)
    assert lib.MXImperativeInvoke(b"square", handles, 1, b"{}", outs,
                                  c.byref(n_out)) == 0
    assert lib.MXAutogradSetIsRecording(0, c.byref(prev)) == 0
    y = (c.c_void_p * 1)(outs[0])
    var_grads = c.POINTER(c.c_void_p)()
    stypes = c.POINTER(c.c_int)()
    assert lib.MXAutogradBackwardEx(1, y, None, 1, handles, 0, 0, 1,
                                    c.byref(var_grads),
                                    c.byref(stypes)) == 0, \
        lib.MXGetLastError()
    got = _to_np(lib, c.c_void_p(var_grads[0]), (2,))
    onp.testing.assert_allclose(got, [4.0, 6.0])


def test_dataiter_info_and_misc(lib):
    name = c.c_char_p()
    desc = c.c_char_p()
    num_args = c.c_int()
    an = c.POINTER(c.c_char_p)()
    at = c.POINTER(c.c_char_p)()
    ad = c.POINTER(c.c_char_p)()
    assert lib.MXDataIterGetIterInfo(b"NDArrayIter", c.byref(name),
                                     c.byref(desc), c.byref(num_args),
                                     c.byref(an), c.byref(at),
                                     c.byref(ad)) == 0, lib.MXGetLastError()
    names = [an[i].decode() for i in range(num_args.value)]
    assert "batch_size" in names
    prev = c.c_int()
    assert lib.MXEngineSetBulkSize(20, c.byref(prev)) == 0
    assert lib.MXRandomSeedContext(5, b"cpu") == 0
    assert lib.MXStorageEmptyCache(b"cpu") == 0
    h = c.c_void_p()
    assert lib.MXNDArrayCreateNone(c.byref(h)) == 0
