"""ONNX export/import round trip (reference
python/mxnet/contrib/onnx + tests/python-pytest/onnx/)."""
import struct

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.contrib.onnx import export_model, import_model
from mxnet_tpu.contrib.onnx import _proto as P


def _export_net(net, x, tmp_path, tag):
    y0 = net(x).asnumpy()
    sf, pf = net.export(str(tmp_path / tag))
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        str(tmp_path / tag), 0)
    params = dict(arg_params)
    params.update(aux_params)
    onnx_path = export_model(sym, params, input_shape=[x.shape],
                             onnx_file_path=str(tmp_path / (tag + ".onnx")))
    return y0, onnx_path


def _forward_imported(onnx_path, x):
    sym, arg_params, aux_params = import_model(onnx_path)
    bindings = dict(arg_params)
    bindings.update(aux_params)
    data_name = [n for n in sym.list_inputs()
                 if n not in bindings][0]
    bindings[data_name] = x
    ex = sym.bind(mx.cpu(), bindings)
    return ex.forward()[0].asnumpy()


def test_onnx_mlp_roundtrip(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 8).astype(np.float32))
    y0, path = _export_net(net, x, tmp_path, "mlp")
    np.testing.assert_allclose(_forward_imported(path, x), y0, rtol=1e-5,
                               atol=1e-6)


def test_onnx_conv_bn_pool_roundtrip(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1), gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(5))
    net.initialize()
    x = nd.array(np.random.RandomState(1).randn(2, 3, 8, 8)
                 .astype(np.float32))
    y0, path = _export_net(net, x, tmp_path, "conv")
    np.testing.assert_allclose(_forward_imported(path, x), y0, rtol=1e-4,
                               atol=1e-5)


def test_onnx_resnet18_roundtrip(tmp_path):
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1()
    net.initialize()
    x = nd.array(np.random.RandomState(2).randn(1, 3, 32, 32)
                 .astype(np.float32))
    y0, path = _export_net(net, x, tmp_path, "r18")
    np.testing.assert_allclose(_forward_imported(path, x), y0, rtol=1e-3,
                               atol=1e-4)


def test_onnx_file_structure(tmp_path):
    """The emitted bytes are a structurally-valid ModelProto: parses with
    an independent walk, has ir_version/producer/opset, graph in/outputs."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(3, in_units=2))
    net.initialize()
    x = nd.ones((1, 2))
    _, path = _export_net(net, x, tmp_path, "s")
    raw = open(path, "rb").read()
    fields = P.parse(raw)
    assert fields[1][0] == 8                      # ir_version
    assert b"mxnet_tpu" in fields[2][0]           # producer_name
    opset = P.parse(fields[8][0])
    assert opset[2][0] == 11                      # opset version
    g = P.parse_graph(fields[7][0])
    assert g["inputs"] and g["outputs"] and g["nodes"]
    assert any(n["op_type"] == "Gemm" for n in g["nodes"])
    # initializers carry raw tensor data
    w = [a for n, a in g["initializers"].items() if a.shape == (3, 2)]
    assert w and w[0].dtype == np.float32


def test_onnx_input_shape_recorded(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(3, in_units=4))
    net.initialize()
    x = nd.ones((2, 4))
    _, path = _export_net(net, x, tmp_path, "shp")
    g = P.parse_model(open(path, "rb").read())
    (name, shape, dtype), = g["inputs"]
    assert shape == (2, 4)
    assert dtype == P.FLOAT


def test_onnx_fix_gamma_exports_ones(tmp_path):
    """Symbol-level BatchNorm with fix_gamma=True ignores the stored gamma;
    the exported model must use ones, not the stored values."""
    data = mx.sym.var("data")
    out = mx.sym.BatchNorm(data, fix_gamma=True, name="bn0")
    params = {"bn0_gamma": nd.array(np.full((3,), 7.0, np.float32)),
              "bn0_beta": nd.zeros((3,)),
              "bn0_moving_mean": nd.zeros((3,)),
              "bn0_moving_var": nd.ones((3,))}
    path = export_model(out, params, input_shape=[(1, 3, 4, 4)],
                        onnx_file_path=str(tmp_path / "fg.onnx"))
    g = P.parse_model(open(path, "rb").read())
    fixed = [a for n, a in g["initializers"].items() if "fixed_gamma" in n]
    assert fixed and np.all(fixed[0] == 1.0)
    assert "bn0_gamma" not in g["initializers"]  # dead tensor not exported
    x = nd.array(np.random.RandomState(3).randn(1, 3, 4, 4)
                 .astype(np.float32))
    y_src = out.bind(mx.cpu(), dict(params, data=x)).forward()[0].asnumpy()
    np.testing.assert_allclose(_forward_imported(path, x), y_src,
                               rtol=1e-4, atol=1e-5)


def test_onnx_softmax_default_axis_flatten_semantics(tmp_path):
    """An external opset-11 Softmax with no axis attr means axis=1 with
    flatten-to-2D semantics."""
    n = P.node("Softmax", ["data"], ["out"], "sm")
    g = P.graph([n], "g", [P.value_info("data", (2, 3, 4))],
                [P.value_info("out", (2, 3, 4))], [])
    path = str(tmp_path / "sm.onnx")
    open(path, "wb").write(P.model(g, opset=11))
    sym, arg_params, aux_params = import_model(path)
    x = np.random.RandomState(4).randn(2, 3, 4).astype(np.float32)
    out = sym.bind(mx.cpu(), {"data": nd.array(x)}).forward()[0].asnumpy()
    flat = x.reshape(2, -1)
    e = np.exp(flat - flat.max(axis=1, keepdims=True))
    expect = (e / e.sum(axis=1, keepdims=True)).reshape(x.shape)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_onnx_softmax_explicit_axis_coerce_semantics(tmp_path):
    """opset-11 Softmax(axis=1) on a 3-D tensor normalizes jointly over
    the flattened trailing block, not per-axis."""
    n = P.node("Softmax", ["data"], ["out"], "sm", axis=1)
    g = P.graph([n], "g", [P.value_info("data", (2, 3, 4))],
                [P.value_info("out", (2, 3, 4))], [])
    path = str(tmp_path / "sm1.onnx")
    open(path, "wb").write(P.model(g, opset=11))
    sym, _, _ = import_model(path)
    x = np.random.RandomState(6).randn(2, 3, 4).astype(np.float32)
    out = sym.bind(mx.cpu(), {"data": nd.array(x)}).forward()[0].asnumpy()
    flat = x.reshape(2, -1)
    e = np.exp(flat - flat.max(axis=1, keepdims=True))
    expect = (e / e.sum(axis=1, keepdims=True)).reshape(x.shape)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_onnx_unpacked_float_data_tensor(tmp_path):
    """TensorProto float_data in unpacked repeated encoding (wire type 5)
    must be bit-reinterpreted, not value-cast."""
    import struct as st
    body = P.w_packed_int64(1, (2,)) + P.w_varint(2, P.FLOAT)
    body += P.w_bytes(8, "w")
    for v in (1.0, 2.5):
        body += P._tag(4, 5) + st.pack("<f", v)
    name, arr = P.parse_tensor(body)
    np.testing.assert_allclose(arr, np.float32([1.0, 2.5]))


def test_onnx_auto_pad_rejected(tmp_path):
    n = P.node("Conv", ["data", "w"], ["out"], "c0",
               kernel_shape=[3, 3], auto_pad="SAME_UPPER")
    g = P.graph([n], "g", [P.value_info("data", (1, 1, 4, 4))],
                [P.value_info("out", (1, 1, 4, 4))],
                [P.tensor_proto("w", np.zeros((1, 1, 3, 3), np.float32))])
    path = str(tmp_path / "ap.onnx")
    open(path, "wb").write(P.model(g, opset=11))
    try:
        import_model(path)
    except NotImplementedError as e:
        assert "auto_pad" in str(e)
    else:
        raise AssertionError("expected NotImplementedError for auto_pad")


def test_onnx_pooling_ceil_mode_roundtrip(tmp_path):
    """'full' pooling convention (gluon ceil_mode=True, e.g. SqueezeNet)
    must survive as ONNX ceil_mode — losing it shrinks feature maps."""
    data = mx.sym.var("data")
    out = mx.sym.Pooling(data, kernel=(3, 3), stride=(2, 2),
                         pool_type="max", pooling_convention="full",
                         name="p0")
    x = nd.array(np.random.RandomState(5).randn(1, 2, 8, 8)
                 .astype(np.float32))
    y0 = out.bind(mx.cpu(), {"data": x}).forward()[0].asnumpy()
    assert y0.shape[-1] == 4  # ceil((8-3)/2)+1; 'valid' would give 3
    path = export_model(out, {}, input_shape=[(1, 2, 8, 8)],
                        onnx_file_path=str(tmp_path / "cm.onnx"))
    g = P.parse_model(open(path, "rb").read())
    (node,) = [n for n in g["nodes"] if n["op_type"] == "MaxPool"]
    assert node["attrs"]["ceil_mode"] == 1
    np.testing.assert_allclose(_forward_imported(path, x), y0, rtol=1e-6)


def test_onnx_unsupported_op_raises(tmp_path):
    data = mx.sym.var("data")
    out = mx.sym.topk(data, k=2)
    try:
        export_model(out, {}, input_shape=[(2, 5)],
                     onnx_file_path=str(tmp_path / "x.onnx"))
    except NotImplementedError as e:
        assert "topk" in str(e)
    else:
        raise AssertionError("expected NotImplementedError")
