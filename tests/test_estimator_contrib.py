"""Estimator API, gluon.contrib layers/cells, legacy FeedForward, and the
MXNET_* env-knob system (reference gluon/contrib/estimator/,
gluon/contrib/nn, model.py FeedForward, env_var.md)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn
from mxnet_tpu.gluon.contrib.estimator import (
    Estimator, EarlyStoppingHandler, CheckpointHandler, LoggingHandler,
    EpochEnd)

R = np.random.RandomState(21)


def _toy_loader(n=64, batch=16):
    X = nd.array(R.randn(n, 4).astype(np.float32))
    Y = nd.array((R.randn(n) > 0).astype(np.float32))
    return gluon.data.DataLoader(gluon.data.ArrayDataset(X, Y),
                                 batch_size=batch)


def _toy_net():
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
    return net


# -------------------------------------------------------------- estimator

def test_estimator_fit_epochs_runs_metrics():
    est = Estimator(_toy_net(), loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    est.fit(train_data=_toy_loader(), epochs=2)
    name, acc = est.train_metrics[0].get()
    assert 0.0 <= acc <= 1.0
    lname, lval = est.train_loss_metrics[0].get()
    assert np.isfinite(lval)


def test_estimator_fit_batches_stops():
    seen = []

    class CountBatches(EpochEnd):
        def epoch_end(self, estimator, *a, **k):
            seen.append(1)

    est = Estimator(_toy_net(), loss=gluon.loss.SoftmaxCrossEntropyLoss())
    est.fit(train_data=_toy_loader(), batches=3,
            event_handlers=[CountBatches()])
    assert est.stop_training


def test_estimator_validation_handler():
    est = Estimator(_toy_net(), loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    est.fit(train_data=_toy_loader(), val_data=_toy_loader(), epochs=1)
    _, vloss = est.val_loss_metrics[0].get()
    assert np.isfinite(vloss)


def test_estimator_early_stopping():
    est = Estimator(_toy_net(), loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    stopper = EarlyStoppingHandler(monitor=est.train_loss_metrics[0],
                                   patience=0, mode="max", min_delta=1e9)
    est.fit(train_data=_toy_loader(), epochs=50,
            event_handlers=[stopper])
    # impossible-improvement monitor -> stop after first epochs, not 50
    assert stopper.current_epoch < 50


def test_estimator_checkpoint_handler(tmp_path):
    est = Estimator(_toy_net(), loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    ckpt = CheckpointHandler(str(tmp_path), model_prefix="m",
                             max_checkpoints=2)
    est.fit(train_data=_toy_loader(), epochs=3, event_handlers=[ckpt])
    files = sorted(os.listdir(str(tmp_path)))
    assert len([f for f in files if f.endswith(".params")]) == 2  # capped


def test_estimator_rejects_non_dataloader():
    est = Estimator(_toy_net(), loss=gluon.loss.SoftmaxCrossEntropyLoss())
    with pytest.raises(ValueError):
        est.fit(train_data=[1, 2, 3], epochs=1)
    with pytest.raises(ValueError):
        est.fit(train_data=_toy_loader())  # neither epochs nor batches


# ---------------------------------------------------------- contrib layers

def test_concurrent_and_identity():
    net = cnn.HybridConcurrent(axis=1)
    net.add(gluon.nn.Dense(3, in_units=4), cnn.Identity())
    net.initialize()
    out = net(nd.ones((2, 4)))
    assert out.shape == (2, 7)
    net2 = cnn.Concurrent(axis=1)
    net2.add(gluon.nn.Dense(2, in_units=4), cnn.Identity())
    net2.initialize()
    assert net2(nd.ones((2, 4))).shape == (2, 6)


def test_pixelshuffle_layers():
    assert cnn.PixelShuffle1D(3)(nd.ones((1, 6, 5))).shape == (1, 2, 15)
    x = nd.array(np.arange(8 * 4, dtype=np.float32).reshape(1, 8, 2, 2))
    y = cnn.PixelShuffle2D(2)(x)
    assert y.shape == (1, 2, 4, 4)
    # content check vs manual depth-to-space of the first output channel
    xn = x.asnumpy()[0]
    expect00 = np.array([[xn[0, 0, 0], xn[1, 0, 0]],
                         [xn[2, 0, 0], xn[3, 0, 0]]], np.float32)
    np.testing.assert_array_equal(y.asnumpy()[0, 0, :2, :2], expect00)
    assert cnn.PixelShuffle3D(2)(nd.ones((1, 16, 2, 2, 2))).shape == \
        (1, 2, 4, 4, 4)


def test_sync_batchnorm_and_sparse_embedding():
    sb = cnn.SyncBatchNorm(in_channels=4, num_devices=8)
    sb.initialize()
    assert sb(nd.ones((2, 4, 3, 3))).shape == (2, 4, 3, 3)
    emb = cnn.SparseEmbedding(10, 6)
    emb.initialize()
    out = emb(nd.array(np.array([1, 5], np.float32)))
    assert out.shape == (2, 6)


def test_lstmp_cell_projection():
    cell = crnn.LSTMPCell(8, 4, input_size=5)
    cell.initialize()
    out, states = cell(nd.ones((2, 5)), cell.begin_state(2))
    assert out.shape == (2, 4)
    assert states[0].shape == (2, 4) and states[1].shape == (2, 8)


def test_variational_dropout_mask_consistent():
    base = gluon.rnn.RNNCell(4, input_size=4)
    vd = crnn.VariationalDropoutCell(base, drop_outputs=0.5)
    vd.initialize()
    from mxnet_tpu import _tape
    prev = _tape.set_training(True)
    try:
        states = vd.begin_state(2)
        out1, states = vd(nd.ones((2, 4)), states)
        mask1 = vd._output_mask.asnumpy()
        out2, states = vd(nd.ones((2, 4)), states)
        mask2 = vd._output_mask.asnumpy()
        np.testing.assert_array_equal(mask1, mask2)  # same mask all steps
        vd.reset()
        assert vd._output_mask is None
    finally:
        _tape.set_training(prev)


# ------------------------------------------------------------- FeedForward

def _ff_symbol():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                name="softmax")


def test_feedforward_fit_predict_save_load(tmp_path):
    X = R.randn(64, 5).astype(np.float32)
    Y = (X.sum(axis=1) > 0).astype(np.float32)
    # lr tuned for reference gradient semantics: Module injects
    # rescale_grad=1/batch (round-4 parity fix), so per-example scale
    model = mx.model.FeedForward(_ff_symbol(), num_epoch=8,
                                 optimizer="sgd", learning_rate=0.5,
                                 numpy_batch_size=16)
    model.fit(X, Y)
    pred = model.predict(X)
    assert pred.shape == (64, 2)
    acc = (pred.argmax(axis=1) == Y).mean()
    assert acc > 0.75, acc
    prefix = str(tmp_path / "ff")
    model.save(prefix)
    loaded = mx.model.FeedForward.load(prefix, 8)
    np.testing.assert_allclose(loaded.predict(X), pred, atol=1e-5)


def test_feedforward_create():
    X = R.randn(32, 5).astype(np.float32)
    Y = (X.sum(axis=1) > 0).astype(np.float32)
    model = mx.model.FeedForward.create(_ff_symbol(), X, Y, num_epoch=1,
                                        learning_rate=0.05,
                                        numpy_batch_size=16)
    assert model.arg_params


# ------------------------------------------------------------- env config

def test_config_registry_covers_reference_knobs():
    from mxnet_tpu import config
    assert len(config.KNOBS) >= 55
    for name in ("MXNET_ENGINE_TYPE", "MXNET_CPU_WORKER_NTHREADS",
                 "MXNET_CUDNN_AUTOTUNE_DEFAULT", "MXNET_KVSTORE_USETREE",
                 "MXNET_HOME"):
        assert name in config.KNOBS
    table = config.describe()
    assert "MXNET_USE_FUSION" in table and "subsumed" in table


def test_config_typed_get(monkeypatch):
    from mxnet_tpu import config
    monkeypatch.setenv("MXNET_CPU_WORKER_NTHREADS", "7")
    assert config.get("MXNET_CPU_WORKER_NTHREADS") == 7
    monkeypatch.setenv("MXNET_EXEC_ENABLE_INPLACE", "false")
    assert config.get("MXNET_EXEC_ENABLE_INPLACE") is False
    monkeypatch.delenv("MXNET_CPU_WORKER_NTHREADS")
    assert config.get("MXNET_CPU_WORKER_NTHREADS") == 1  # reference default


def test_config_update_on_kvstore(monkeypatch):
    from mxnet_tpu.model import _create_kvstore
    monkeypatch.setenv("MXNET_UPDATE_ON_KVSTORE", "1")
    _, update = _create_kvstore("local", 2, {})
    assert update is True
    monkeypatch.delenv("MXNET_UPDATE_ON_KVSTORE")
    _, update = _create_kvstore("local", 2, {})
    assert update is False
