"""Engine semantics: NaiveEngine blocking dispatch + bulk API.

Reference model: ``tests/python/unittest/test_engine.py`` (bulk size API)
and ``test_exc_handling.py`` (async exception propagation: errors surface
at sync points by default, synchronously under MXNET_ENGINE_TYPE=NaiveEngine,
`src/engine/naive_engine.cc`).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine
from mxnet_tpu.ops.registry import register, get_op


def test_bulk_size_api():
    prev = engine.set_bulk_size(10)
    assert engine.set_bulk_size(prev) == 10
    with engine.bulk(7):
        assert engine._bulk_size[0] == 7


def test_naive_engine_blocks_dispatch():
    engine.set_naive(True)
    try:
        a = mx.nd.ones((64, 64))
        b = mx.nd.dot(a, a)
        # NaiveEngine serializes: the result buffer is ready the moment the
        # op call returns (no async dispatch window).
        assert b._data.is_ready()
        np.testing.assert_allclose(b.asnumpy(), np.full((64, 64), 64.0))
    finally:
        engine.set_naive(False)


def _get_failing_op():
    if get_op("_test_engine_fail") is None:
        import jax

        @register("_test_engine_fail", differentiable=False)
        def _test_engine_fail(x):
            def cb(v):
                raise ValueError("engine-test deliberate failure")
            return jax.pure_callback(
                cb, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    return get_op("_test_engine_fail")


def test_naive_engine_synchronous_exception():
    """With NaiveEngine, a device-side failure raises inside the op call
    itself (reference test_exc_handling.py semantics)."""
    op = _get_failing_op()
    engine.set_naive(True)
    try:
        with pytest.raises(Exception, match="deliberate failure"):
            op(mx.nd.ones((4,)))
    finally:
        engine.set_naive(False)


def test_async_exception_surfaces_at_sync_point():
    """Default engine: the failure surfaces no later than wait_to_read /
    asnumpy (the reference's WaitForVar rethrow semantics,
    `src/engine/threaded_engine.h:463`)."""
    op = _get_failing_op()
    with pytest.raises(Exception, match="deliberate failure"):
        out = op(mx.nd.ones((4,)))
        out.wait_to_read()


def test_naive_engine_does_not_break_tracing():
    import jax
    import jax.numpy as jnp

    engine.set_naive(True)
    try:
        f = jax.jit(lambda x: jnp.tanh(x) * 2)
        np.testing.assert_allclose(
            np.asarray(f(jnp.ones(3))), np.tanh(np.ones(3)) * 2, rtol=1e-6)
        # an eager framework op under naive mode still composes with jit
        a = mx.nd.ones((8,))
        assert float(mx.nd.sum(a).asnumpy()) == 8.0
    finally:
        engine.set_naive(False)
