"""E2E tests for the round-4 example ports (VERDICT r3 item 5): sparse
linear classification, model-parallel, module workflow, numpy-ops
CustomOp, quantization calibrate->deploy, denoising autoencoder,
profiler trace. Each drives the example's
`train`/`main` entry exactly as the CLI does and asserts the capability
the reference example demonstrates."""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for sub in ("sparse", "model-parallel", "module", "numpy-ops",
            "quantization", "autoencoder", "profiler"):
    sys.path.insert(0, os.path.join(REPO, "example", sub))


def test_sparse_linear_classification():
    """Row-sparse weight in the kvstore, row_sparse_pull per batch,
    row-sparse gradient push through the store-side optimizer."""
    from linear_classification import train, load_libsvm
    losses, acc, w_final, w_true = train(epochs=6, log=lambda *a: None)
    assert losses[-1] < losses[0] * 0.8
    assert acc > 0.8, acc
    assert w_final.shape == (1000, 1)
    # rows never touched by any sample must still be zero (the updates
    # really were row-sparse)
    csr, _ = load_libsvm("/tmp/sparse_linear.libsvm", 1000)
    touched = np.nonzero(csr.asnumpy().any(axis=0))[0]
    untouched = np.setdiff1d(np.arange(1000), touched)
    assert untouched.size > 0
    np.testing.assert_array_equal(w_final[untouched], 0.0)


def test_model_parallel_mlp():
    """Two pipeline stages on two devices via group2ctx; training crosses
    the device boundary forward and backward."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    from mlp_model_parallel import train
    first, last, n_devices = train(steps=300, log=lambda *a: None)
    assert n_devices == 2
    assert last < first * 0.8, (first, last)


def test_module_workflow():
    """fit -> checkpoint -> resume -> score -> predict (reference
    example/module)."""
    from mnist_module import train
    acc, preds = train(epochs=4, log=lambda *a: None)
    assert acc > 0.9, acc
    assert preds.shape[1] == 10


def test_numpy_ops_custom_softmax():
    """A numpy CustomOp as the loss layer of a Module-trained net."""
    from custom_softmax import train
    acc = train(epochs=6, log=lambda *a: None)
    assert acc > 0.8, acc


def test_quantization_calibrate_deploy():
    """fp32 train -> naive calibration -> int8 swap -> save/reload."""
    from quantize_deploy import main
    acc_fp32, acc_int8, acc_loaded = main(epochs=3, log=lambda *a: None)
    assert acc_fp32 > 0.9
    assert acc_int8 > acc_fp32 - 0.05
    assert abs(acc_loaded - acc_int8) < 0.02


def test_autoencoder_denoising():
    """Denoising AE recovers the low-rank manifold: reconstruction MSE
    drops and beats the data variance by a wide margin."""
    from train_autoencoder import train, make_data
    first, last, rec_mse = train(epochs=12, log=lambda *a: None)
    assert last < first * 0.6, (first, last)
    var = float(make_data().var())
    assert rec_mse < 0.5 * var, (rec_mse, var)


def test_profiler_example_produces_trace(tmp_path):
    """The profiler example yields a non-empty XPlane trace."""
    from profile_training import train_profiled
    traces = train_profiled(steps=8, outdir=str(tmp_path),
                            log=lambda *a: None)
    assert traces, "no trace files written"
    assert any(os.path.getsize(t) > 10000 for t in traces)
