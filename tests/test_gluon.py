"""Gluon Block/layer tests — semantics ported from the reference suite
(`tests/python/unittest/test_gluon.py`), rewritten for the TPU build."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier", ctx=[mx.cpu(0)])
    assert len(p.list_data()) == 1
    assert len(p.list_grad()) == 1
    assert p.data(mx.cpu(0)).ctx == mx.cpu(0)
    assert p.data(mx.cpu(0)).shape == (10, 10)
    assert p.grad_req == "write"


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu())


def test_constant():
    class Test(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.value = np.asarray([[1, 2], [3, 4.]])
            self.const = self.params.get_constant("const", self.value)

        def hybrid_forward(self, F, x, const):
            return x + const

    test = Test()
    test.initialize()
    trainer = gluon.Trainer(test.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.5})
    with ag.record():
        x = mx.nd.ones((2, 2))
        x.attach_grad()
        y = test(x)
        y.backward()
    trainer.step(1)
    assert (test.const.data().asnumpy() == test.value).all()
    assert (x.grad.asnumpy() == 1).all()


def test_basic_dense():
    model = nn.Sequential()
    model.add(nn.Dense(128, activation="tanh", in_units=10, flatten=False),
              nn.Dropout(0.5),
              nn.Dense(64, activation="tanh", in_units=256),
              nn.Dense(32, in_units=64))
    model.initialize()
    # ndarray
    x = mx.nd.array(np.random.uniform(size=(32, 2, 10)))
    out = model(x)
    assert out.shape == (32, 32)


def test_dense_flatten_false():
    model = nn.Dense(32, in_units=10, flatten=False)
    model.initialize()
    x = mx.nd.array(np.random.uniform(size=(4, 7, 10)))
    assert model(x).shape == (4, 7, 32)


def test_dense_deferred_init():
    model = nn.Dense(32)
    model.initialize()
    x = mx.nd.array(np.random.uniform(size=(8, 12)))
    assert model(x).shape == (8, 32)
    assert model.weight.shape == (32, 12)


def test_sequential_getitem():
    net = nn.Sequential()
    for _ in range(5):
        net.add(nn.Dense(4, in_units=4))
    assert isinstance(net[1], nn.Dense)
    assert len(net[2:4]) == 2


def test_hybrid_sequential_vs_eager():
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.randn(2, 8).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_conv_layers():
    for layer, shape in [
            (nn.Conv1D(16, 3, in_channels=4), (2, 4, 10)),
            (nn.Conv2D(16, (3, 4), in_channels=4), (2, 4, 10, 10)),
            (nn.Conv3D(16, (1, 8, 4), in_channels=4, activation="relu"),
             (2, 4, 10, 10, 10)),
            (nn.Conv2D(16, (3, 3), groups=2, in_channels=4), (2, 4, 10, 10)),
    ]:
        layer.initialize()
        x = mx.nd.array(np.random.rand(*shape).astype("float32"))
        with ag.record():
            out = layer(x)
            out.backward()
        assert out.shape[0] == shape[0] and out.shape[1] == 16


def test_deconv_layers():
    layer = nn.Conv2DTranspose(16, (3, 3), strides=2, in_channels=4)
    layer.initialize()
    x = mx.nd.array(np.random.rand(2, 4, 8, 8).astype("float32"))
    out = layer(x)
    assert out.shape == (2, 16, 17, 17)


def test_pool_layers():
    x = mx.nd.array(np.random.rand(2, 3, 8, 8).astype("float32"))
    assert nn.MaxPool2D()(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(pool_size=3, strides=1, padding=1)(x).shape == \
        (2, 3, 8, 8)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)
    # ceil mode
    assert nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True)(x).shape == \
        (2, 3, 4, 4)


def test_batchnorm_running_stats():
    layer = nn.BatchNorm(in_channels=4)
    layer.initialize()
    x = mx.nd.array(np.random.rand(8, 4, 5, 5).astype("float32") * 2 + 3)
    with ag.record():
        layer(x)
    rm = layer.running_mean.data().asnumpy()
    assert not np.allclose(rm, np.zeros(4))
    # predict mode uses running stats: output not normalized to zero mean
    out = layer(x).asnumpy()
    assert abs(out.mean()) > 1e-3


def test_layernorm():
    layer = nn.LayerNorm(in_channels=10)
    layer.initialize()
    x = mx.nd.array(np.random.rand(2, 5, 10).astype("float32"))
    out = layer(x).asnumpy()
    np.testing.assert_allclose(out.mean(-1), np.zeros((2, 5)), atol=1e-5)
    np.testing.assert_allclose(out.std(-1), np.ones((2, 5)), rtol=1e-1)


def test_instancenorm_groupnorm():
    x = mx.nd.array(np.random.rand(2, 4, 4, 4).astype("float32"))
    for layer in [nn.InstanceNorm(in_channels=4),
                  nn.GroupNorm(num_groups=2, in_channels=4)]:
        layer.initialize()
        assert layer(x).shape == x.shape


def test_embedding():
    layer = nn.Embedding(10, 5)
    layer.initialize()
    x = mx.nd.array(np.array([0, 2, 4]))
    with ag.record():
        y = layer(x)
        y.sum().backward()
    assert y.shape == (3, 5)
    grad = layer.weight.grad().asnumpy()
    assert grad[0].sum() != 0 and grad[1].sum() == 0


def test_activations_blocks():
    x = mx.nd.array(np.array([-1.0, 0.0, 2.0], dtype="float32"))
    for blk in [nn.LeakyReLU(0.1), nn.ELU(), nn.SELU(), nn.Swish(),
                nn.GELU(), nn.Activation("relu"), nn.Activation("tanh")]:
        if hasattr(blk, "initialize"):
            blk.initialize()
        out = blk(x)
        assert out.shape == x.shape
    prelu = nn.PReLU()
    prelu.initialize()
    out = prelu(x).asnumpy()
    np.testing.assert_allclose(out, np.array([-0.25, 0, 2.0]), atol=1e-6)


def test_flatten_lambda():
    x = mx.nd.array(np.random.rand(2, 3, 4).astype("float32"))
    assert nn.Flatten()(x).shape == (2, 12)
    lam = nn.Lambda(lambda x: x * 2)
    np.testing.assert_allclose(lam(x).asnumpy(), x.asnumpy() * 2, rtol=1e-6)
    hlam = nn.HybridLambda(lambda F, x: F.relu(x))
    assert hlam(x).shape == x.shape


def test_block_attr_registry():
    class Model(gluon.Block):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.dense0 = nn.Dense(5, in_units=5)
                self.dense1 = nn.Dense(5, in_units=5)

        def forward(self, x):
            return self.dense1(self.dense0(x))

    model = Model()
    assert len(model.collect_params()) == 4
    model.initialize()
    out = model(mx.nd.zeros((2, 5)))
    assert out.shape == (2, 5)


def test_collect_params_select():
    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=4), nn.Dense(4, in_units=4))
    weights = net.collect_params(".*weight")
    assert all(k.endswith("weight") for k in weights.keys())
    assert len(weights) == 2


def test_save_load_parameters_roundtrip(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3).astype("float32"))
    ref = net(x).asnumpy()
    path = str(tmp_path / "net.params")
    net.save_parameters(path)
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(path)
    np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-6)


def test_losses():
    np.random.seed(0)
    pred = mx.nd.array(np.random.randn(8, 4).astype("float32"))
    label_idx = mx.nd.array(np.random.randint(0, 4, 8))
    label_dense = mx.nd.array(np.random.rand(8, 4).astype("float32"))

    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label_idx)
    assert l.shape == (8,)
    # matches manual computation
    p = pred.asnumpy()
    logp = p - np.log(np.exp(p).sum(1, keepdims=True))
    want = -logp[np.arange(8), label_idx.asnumpy().astype(int)]
    np.testing.assert_allclose(l.asnumpy(), want, rtol=1e-5)

    assert gluon.loss.L2Loss()(pred, label_dense).shape == (8,)
    assert gluon.loss.L1Loss()(pred, label_dense).shape == (8,)
    assert gluon.loss.SigmoidBCELoss()(pred, label_dense).shape == (8,)
    assert gluon.loss.KLDivLoss()(mx.nd.log_softmax(pred),
                                  mx.nd.softmax(label_dense)).shape == (8,)
    assert gluon.loss.HuberLoss()(pred, label_dense).shape == (8,)
    assert gluon.loss.HingeLoss()(pred, label_dense).shape == (8,)
    assert gluon.loss.SquaredHingeLoss()(pred, label_dense).shape == (8,)
    assert gluon.loss.LogisticLoss()(pred.sum(1), label_idx).shape == (8,)
    assert gluon.loss.TripletLoss()(pred, label_dense,
                                    label_dense * 0.5).shape == (8,)
    assert gluon.loss.PoissonNLLLoss()(pred, label_dense).shape == ()
    cos = gluon.loss.CosineEmbeddingLoss()(
        pred, label_dense, mx.nd.array(np.sign(np.random.randn(8))))
    assert cos.shape == (8,)


def test_ctc_loss():
    loss = gluon.loss.CTCLoss()
    # uniform predictions over 4 classes +1 blank; 2 label steps
    pred = mx.nd.zeros((2, 20, 5))
    label = mx.nd.array(np.array([[1, 2], [2, 3]], dtype="float32"))
    l = loss(pred, label)
    assert l.shape == (2,)
    assert np.isfinite(l.asnumpy()).all()
    # known value check vs. manually verified alpha recursion on tiny case
    with ag.record():
        p = mx.nd.zeros((1, 3, 3))
        p.attach_grad()
        out = loss(p, mx.nd.array(np.array([[1.0]])))
    out.backward()
    assert np.isfinite(p.grad.asnumpy()).all()


def test_trainer_basic():
    p = gluon.Parameter("w", shape=(4,))
    p.initialize(init="ones", ctx=mx.cpu())
    trainer = gluon.Trainer({"w": p}, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.0})
    with ag.record():
        loss = (p.data() * 2.0).sum()
    loss.backward()
    trainer.step(1)
    np.testing.assert_allclose(p.data().asnumpy(), np.ones(4) - 0.2,
                               rtol=1e-6)
    assert trainer.learning_rate == 0.1
    trainer.set_learning_rate(0.2)
    assert trainer.learning_rate == 0.2


def test_trainer_save_load_states(tmp_path):
    p = gluon.Parameter("w", shape=(4,))
    p.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer({"w": p}, "adam", {"learning_rate": 0.1})
    with ag.record():
        ((p.data() ** 2).sum()).backward()
    trainer.step(1)
    path = str(tmp_path / "tr.states")
    trainer.save_states(path)
    trainer2 = gluon.Trainer({"w": p}, "adam", {"learning_rate": 0.1})
    trainer2.load_states(path)
    assert trainer2._updaters[0].states.keys() == \
        trainer._updaters[0].states.keys()


def test_clip_global_norm():
    arrays = [mx.nd.ones((3,)) * 2 for _ in range(2)]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert total <= 1.0 + 1e-5
    assert norm > 1.0


def test_split_and_load():
    data = mx.nd.array(np.arange(16).reshape(8, 2))
    splits = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert len(splits) == 2
    assert splits[0].shape == (4, 2)
    with pytest.raises(ValueError):
        gluon.utils.split_data(mx.nd.zeros((5, 2)), 2)
