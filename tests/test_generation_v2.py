"""Generation serving v2 tests — copy-on-admit prefix cache, chunked
prefill lanes, speculative decoding (ISSUE 14).

Acceptance criteria covered on the CPU oracle:
(a) prefix-cache correctness: a hit path produces BITWISE-equal arena
    content and greedy streams vs a cold prefill, refcounts block
    eviction of in-use slabs, LRU eviction respects the byte budget,
    and a forced hash-chain collision degrades to a miss;
(b) chunked prefill: long prompts interleave with decode iterations
    (live streams keep emitting while a long prompt prefills) and the
    result is token-exact vs the monolithic path;
(c) speculative decoding: greedy streams are token-exact vs the plain
    scheduler with ANY draft (an adversarial random draft and a
    self-draft), acceptance accounting is sane, and the verify program
    compiles ONCE;
(d) deadline-aware admission (the prefill-starvation fix), kvcache
    hwm/slots_peak/fragmentation stats, fleet gen_lane policy, and the
    bench_diff directions for the new GENERATION.json fields.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import TransformerLM, transformer_lm_tiny
from mxnet_tpu.serving import DeadlineExceeded, ServingError
from mxnet_tpu.serving.generation import (DecodeEngine, GenerationScheduler,
                                          PrefixCache, SlotKVCache,
                                          SpeculativeDecoder)
from mxnet_tpu.serving.generation import prefix_cache as _pc_mod

VOCAB = 64


@pytest.fixture(scope="module")
def tiny_lm():
    np.random.seed(0)
    net = transformer_lm_tiny(vocab_size=VOCAB)
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((1, 8), "int32")))
    return net


@pytest.fixture(scope="module")
def draft_lm():
    """A structurally different, independently initialized draft — the
    adversarial case: near-zero agreement with the target, so the
    token-exactness guarantee cannot hide behind acceptance."""
    np.random.seed(123)
    net = TransformerLM(VOCAB, units=32, num_layers=1, num_heads=2,
                        max_len=256)
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((1, 8), "int32")))
    return net


def _ref_greedy(net, prompt, n):
    """Independent reference: greedy token i via ONE full forward over
    the prefix (mathematically identical to per-token re-prefill)."""
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits = net(nd.array(np.asarray(seq, "int32")[None]))
        t = int(logits.asnumpy()[0, -1].argmax())
        out.append(t)
        seq.append(t)
    return out


def _engine(net, slots=4, max_seq=64, ladder=(8, 16, 32), **kw):
    return DecodeEngine(net, num_slots=slots, max_seq=max_seq,
                        ladder=ladder, **kw)


# ---------------------------------------------------------------------------
# (a) prefix cache
# ---------------------------------------------------------------------------

def test_prefix_hit_bitwise_equals_cold(tiny_lm):
    """The headline invariant: a prefix-cache hit installs BITWISE the
    same arena content a cold chunked prefill computes, the first-token
    logits path samples the same token, and the greedy continuation is
    bitwise the same stream."""
    pc = PrefixCache(block=8, name="px.bw")
    eng = _engine(tiny_lm, chunk=8, prefix_cache=pc, name="px.bw")
    try:
        prompt = np.random.default_rng(1).integers(
            1, VOCAB, size=21).astype("int32")
        s_cold = eng.cache.acquire()
        _, tok_cold = eng.prefill_chunks(s_cold, prompt, 0)
        eng.prefix_store(s_cold, prompt)

        s_hit = eng.cache.acquire()
        skipped = eng.prefix_admit(s_hit, prompt)
        assert skipped == 16  # largest block multiple <= n-1
        _, tok_hit = eng.prefill_chunks(s_hit, prompt, skipped)
        assert tok_hit == tok_cold

        k = eng.cache.k_arena.asnumpy()
        v = eng.cache.v_arena.asnumpy()
        n = len(prompt)
        assert np.array_equal(k[:, s_cold, :n], k[:, s_hit, :n])
        assert np.array_equal(v[:, s_cold, :n], v[:, s_hit, :n])

        toks = np.zeros(eng.num_slots, np.int32)
        temps = np.zeros(eng.num_slots, np.float32)
        toks[s_cold], toks[s_hit] = tok_cold, tok_hit
        a, b = [tok_cold], [tok_hit]
        for _ in range(6):
            out = eng.decode_step(toks, temps)
            eng.cache.advance([s_cold, s_hit])
            toks[s_cold], toks[s_hit] = out[s_cold], out[s_hit]
            a.append(int(out[s_cold]))
            b.append(int(out[s_hit]))
        assert a == b
        assert a == _ref_greedy(tiny_lm, prompt, 7)
    finally:
        eng.close()


def test_prefix_stats_and_profiler_rows(tiny_lm):
    from mxnet_tpu import profiler
    pc = PrefixCache(block=4, name="px.rows")
    eng = _engine(tiny_lm, chunk=4, prefix_cache=pc, name="px.rows")
    sched = GenerationScheduler(eng, retry_policy=False, name="px.rows")
    try:
        prompt = list(range(1, 14))
        sched.submit(prompt, max_new_tokens=3).result(timeout=120)
        eng.prefix_flush()   # publishing is async; land it before resubmit
        sched.submit(prompt, max_new_tokens=3).result(timeout=120)
        st = pc.stats()
        assert st["hits"] == 1 and st["insertions"] >= 3
        assert st["tokens_saved"] == 12
        assert st["hit_rate"] == 0.5
        rows = profiler.get_aggregate_stats()
        for key in ("hits", "misses", "tokens_saved", "evictions"):
            assert "generation.prefix.px.rows.%s" % key in rows
        sst = sched.stats()
        assert sst["prefix_hits"] == 1
        assert sst["prefix_tokens_saved"] == 12
    finally:
        sched.close()
        eng.close()


def test_prefix_refcount_blocks_eviction():
    """An acquired (in-copy) slab survives eviction pressure; releasing
    it makes it evictable again."""
    pc = PrefixCache(block=2, capacity_mb=1, name="px.ref")
    slab = np.zeros((2, 1, 2, 2, 64), "float32")  # 2 KiB per k+v pair
    pc.insert([1, 2], slab, slab)
    hit = pc.lookup([1, 2, 3])
    assert hit is not None
    entry, plen = hit
    assert plen == 2 and entry.refs == 1
    # flood far past the 1 MiB budget while the entry is held
    big = np.zeros((2, 1, 2, 64, 512), "float32")  # 512 KiB per pair
    for i in range(6):
        pc.insert([10 + i, 20 + i], big, big)
    assert pc.stats()["evictions"] > 0
    hit2 = pc.lookup([1, 2, 99])
    assert hit2 is not None                    # still resident
    pc.release(hit2[0])
    pc.release(entry)
    # with refs=0 the next pressure wave may evict it
    for i in range(6):
        pc.insert([50 + i, 60 + i], big, big)
    assert pc.lookup([1, 2, 3]) is None
    assert pc.stats()["bytes"] <= pc.capacity_bytes
    pc.close()


def test_prefix_lru_eviction_under_pressure():
    pc = PrefixCache(block=2, capacity_mb=1, name="px.lru")
    big = np.zeros((2, 1, 2, 64, 256), "float32")  # 256 KiB per pair
    for i in range(8):
        pc.insert([i, i + 100], big, big)
    st = pc.stats()
    assert st["evictions"] >= 4
    assert st["bytes"] <= pc.capacity_bytes
    # oldest entries gone, newest present
    assert pc.lookup([0, 100, 1]) is None
    assert pc.lookup([7, 107, 1]) is not None
    pc.close()


def test_prefix_hash_chain_collision_safety(monkeypatch):
    """Force every prefix onto one hash value: the stored token run must
    reject the look-alike and count a collision instead of serving
    another prompt's K/V."""
    monkeypatch.setattr(_pc_mod, "_hash_chain",
                        lambda tokens: [7] * len(tokens))
    pc = PrefixCache(block=2, name="px.col")
    slab = np.ones((1, 1, 2, 1, 4), "float32")
    pc.insert([1, 2], slab, slab)
    assert pc.lookup([3, 4, 5]) is None          # same key, other tokens
    assert pc.stats()["collisions"] == 1
    hit = pc.lookup([1, 2, 9])                   # the real owner still hits
    assert hit is not None and hit[1] == 2
    pc.release(hit[0])
    pc.close()


def test_prefix_block_granularity_disabled_for_short_prompts(tiny_lm):
    """Prompts shorter than one block never touch the cache (the
    back-compat guarantee for the default-on knob)."""
    pc = PrefixCache(block=32, name="px.short")
    eng = _engine(tiny_lm, prefix_cache=pc, name="px.short")
    sched = GenerationScheduler(eng, retry_policy=False)
    try:
        sched.submit([1, 2, 3], max_new_tokens=2).result(timeout=120)
        sched.submit([1, 2, 3], max_new_tokens=2).result(timeout=120)
        st = pc.stats()
        assert st["hits"] == 0 and st["entries"] == 0
    finally:
        sched.close()
        eng.close()


# ---------------------------------------------------------------------------
# (b) chunked prefill
# ---------------------------------------------------------------------------

def test_chunked_prefill_token_exact(tiny_lm):
    """Chunked admission (multiple iterations per prompt) produces the
    reference greedy stream."""
    eng = _engine(tiny_lm, chunk=4, prefix_cache=False, name="ck.exact")
    sched = GenerationScheduler(eng, retry_policy=False)
    try:
        rng = np.random.default_rng(2)
        for L in (5, 11, 19, 30):
            prompt = rng.integers(1, VOCAB, size=L).tolist()
            got = sched.submit(prompt, max_new_tokens=6).result(timeout=120)
            assert got == _ref_greedy(tiny_lm, prompt, 6)
        assert sched.metrics.snapshot()["prefill_chunks"] > 0
    finally:
        sched.close()
        eng.close()


def test_chunked_prefill_interleaves_with_decode(tiny_lm):
    """While a long prompt chunks through prefill, live streams keep
    receiving tokens — the freeze chunking exists to fix."""
    import threading
    eng = _engine(tiny_lm, slots=2, max_seq=64, chunk=4,
                  prefix_cache=False, name="ck.live")
    sched = GenerationScheduler(eng, retry_policy=False)
    try:
        arrivals = []

        def consume(req):
            import time as _t
            for _ in req.tokens(timeout=120):
                arrivals.append(_t.monotonic())

        live = sched.submit(list(range(1, 6)), max_new_tokens=40)
        t = threading.Thread(target=consume, args=(live,))
        t.start()
        while len(arrivals) < 3:   # stream demonstrably decoding
            pass
        long_prompt = np.random.default_rng(3).integers(
            1, VOCAB, size=40).tolist()
        long_req = sched.submit(long_prompt, max_new_tokens=2)
        long_req.result(timeout=120)
        t.join(timeout=120)
        # tokens arrived WHILE the long prompt was prefilling (>= 10
        # chunk iterations between admit and its first token)
        during = [a for a in arrivals
                  if long_req.admitted_t < a < long_req.first_token_t]
        assert len(during) >= 3, (len(during), len(arrivals))
        assert long_req.tokens_out[:2] == \
            _ref_greedy(tiny_lm, long_prompt, 2)
        assert sched.metrics.snapshot()["prefill_chunks"] >= 9
    finally:
        sched.close()
        eng.close()


def test_chunked_admits_prompts_beyond_ladder(tiny_lm):
    """With chunking on, the prompt bound is the arena (max_seq - 1),
    not the monolithic prefill ladder."""
    eng = _engine(tiny_lm, chunk=8, ladder=(8, 16), max_seq=64,
                  prefix_cache=False, name="ck.long")
    sched = GenerationScheduler(eng, retry_policy=False)
    try:
        prompt = np.random.default_rng(4).integers(
            1, VOCAB, size=40).tolist()   # > ladder max (16)
        got = sched.submit(prompt, max_new_tokens=4).result(timeout=120)
        assert got == _ref_greedy(tiny_lm, prompt, 4)
        with pytest.raises(ServingError):
            sched.submit([1] * 64, max_new_tokens=2)  # >= max_seq
    finally:
        sched.close()
        eng.close()


def test_deadline_aware_admission_prevents_starvation(tiny_lm):
    """Regression for the FIFO starvation bug: a burst of budget-heavy
    deadline-less prompts ahead of a short deadline-bearing chat request
    must not expire it in queue — EDF admits the deadline first."""
    eng = _engine(tiny_lm, slots=1, prefix_cache=False, name="edf")
    sched = GenerationScheduler(eng, retry_policy=False)
    try:
        hog = sched.submit([1, 2, 3], max_new_tokens=30)   # occupies slot
        longs = [sched.submit([5] * 8, max_new_tokens=30)
                 for _ in range(3)]                         # FIFO-ahead
        chat = sched.submit([9, 8, 7], max_new_tokens=2,
                            timeout_ms=60000.0)
        assert chat.result(timeout=120)                     # not expired
        hog.result(timeout=120)
        for r in longs:
            r.result(timeout=120)
        assert chat.finish_reason == "length"
        # EDF admitted the deadline-bearing request the moment the hog's
        # slot freed — BEFORE any of the FIFO-ahead deadline-less longs
        # started (under plain FIFO it would have sat behind 3 x 30-token
        # sequences on the single slot)
        assert chat.done_t < min(r.first_token_t for r in longs)
    finally:
        sched.close()
        eng.close()


# ---------------------------------------------------------------------------
# (c) speculative decoding
# ---------------------------------------------------------------------------

def test_speculative_token_exact_adversarial_draft(tiny_lm, draft_lm):
    """Token-exactness with a draft that almost never agrees: every
    emitted token is the target's own greedy choice."""
    rng = np.random.default_rng(6)
    prompts = [rng.integers(1, VOCAB, size=int(n)).tolist()
               for n in (4, 7, 12, 15)]

    eng = _engine(tiny_lm, prefix_cache=False, name="sp.adv")
    sched = GenerationScheduler(eng, retry_policy=False,
                                draft_model=draft_lm)
    try:
        reqs = [sched.submit(p, max_new_tokens=8) for p in prompts]
        outs = [r.result(timeout=120) for r in reqs]
        for p, got in zip(prompts, outs):
            assert got == _ref_greedy(tiny_lm, p, 8)
        st = sched.stats()["speculative"]
        assert st["rounds"] > 0
        assert st["verify"]["misses"] <= 1       # ONE fused verify program
        assert st["acceptance_rate"] < 0.5       # genuinely adversarial
    finally:
        sched.close()
        eng.close()


def test_speculative_self_draft_accepts_everything(tiny_lm):
    """Draft == target weights: every proposal is accepted, each round
    emits k+1 tokens, and the stream is still the reference greedy."""
    np.random.seed(0)
    clone = transformer_lm_tiny(vocab_size=VOCAB)
    clone.initialize(mx.init.Xavier())
    clone(nd.array(np.zeros((1, 8), "int32")))

    eng = _engine(tiny_lm, prefix_cache=False, name="sp.self")
    spec = SpeculativeDecoder(eng, clone, k=3)
    sched = GenerationScheduler(eng, retry_policy=False, speculative=spec)
    try:
        prompt = list(range(1, 9))
        got = sched.submit(prompt, max_new_tokens=9).result(timeout=120)
        assert got == _ref_greedy(tiny_lm, prompt, 9)
        st = spec.stats()
        assert st["acceptance_rate"] == 1.0
        # 1 prefill token + 8 decode tokens at k=3 (4/round) -> 2 rounds
        assert st["rounds"] == 2
        snap = sched.metrics.snapshot()
        assert snap["spec_acceptance_rate"] == 1.0
        assert snap["tokens_out"] == 8   # decode tokens (prefill separate)
    finally:
        sched.close()
        spec.close()
        eng.close()


def test_speculative_mixed_temperature_falls_back(tiny_lm, draft_lm):
    """A sampling request in the batch disables the speculative path for
    those iterations (greedy exactness can't cover sampling) — both
    requests still complete, and greedy-only iterations still
    speculate."""
    eng = _engine(tiny_lm, prefix_cache=False, name="sp.mix")
    sched = GenerationScheduler(eng, retry_policy=False,
                                draft_model=draft_lm)
    try:
        sampled = sched.submit([1, 2, 3, 4], max_new_tokens=12,
                               temperature=0.9)
        greedy = sched.submit([9, 8, 7], max_new_tokens=12)
        assert len(sampled.result(timeout=120)) == 12
        assert greedy.result(timeout=120)
        st = sched.stats()
        assert st["completed"] == 2
    finally:
        sched.close()
        eng.close()


def test_speculative_eos_and_budget_trim(tiny_lm):
    """EOS inside an accepted run stops the stream AT the EOS token and
    budget caps multi-token rounds exactly."""
    np.random.seed(0)
    clone = transformer_lm_tiny(vocab_size=VOCAB)
    clone.initialize(mx.init.Xavier())
    clone(nd.array(np.zeros((1, 8), "int32")))
    prompt = list(range(1, 9))
    ref = _ref_greedy(tiny_lm, prompt, 12)

    eng = _engine(tiny_lm, prefix_cache=False, name="sp.eos")
    sched = GenerationScheduler(eng, retry_policy=False,
                                draft_model=clone)
    try:
        # budget trim: ask for 6 (not a multiple of k+1)
        got = sched.submit(prompt, max_new_tokens=6).result(timeout=120)
        assert got == ref[:6]
        # EOS trim: use the reference's 4th token as eos_id
        got = sched.submit(prompt, max_new_tokens=12,
                           eos_id=ref[3]).result(timeout=120)
        first_eos = ref.index(ref[3])
        assert got == ref[:first_eos + 1]
    finally:
        sched.close()
        eng.close()


def test_speculative_rejects_short_draft(tiny_lm):
    """A draft whose max_len cannot cover the target arena depth fails
    at construction (the mirror arena would be silently clamped and
    crash mid-flight at the draft's edge, failing every live request)."""
    np.random.seed(9)
    short = TransformerLM(VOCAB, units=32, num_layers=1, num_heads=2,
                          max_len=32)
    short.initialize(mx.init.Xavier())
    eng = _engine(tiny_lm, max_seq=64, prefix_cache=False, name="sp.short")
    try:
        with pytest.raises(ValueError, match="max_len"):
            SpeculativeDecoder(eng, short, k=2)
    finally:
        eng.close()


def test_speculative_churn_compiles_nothing(tiny_lm, draft_lm):
    """Membership churn across speculative rounds: ONE decode program,
    ONE verify program, ONE draft decode program — joins/leaves change
    data only."""
    eng = _engine(tiny_lm, slots=3, prefix_cache=False, name="sp.churn")
    spec = SpeculativeDecoder(eng, draft_lm, k=2)
    sched = GenerationScheduler(eng, retry_policy=False, speculative=spec)
    try:
        rng = np.random.default_rng(8)
        reqs = []
        for i in range(7):    # > slots: continuous join/leave
            reqs.append(sched.submit(
                rng.integers(1, VOCAB, size=int(rng.integers(3, 12))
                             ).tolist(),
                max_new_tokens=int(rng.integers(3, 9))))
        for r in reqs:
            r.result(timeout=120)
        # all-greedy traffic speculates every iteration, so the plain
        # decode program may never even compile (<= 1 either way)
        assert eng.compile_stats()["decode"]["misses"] <= 1
        assert spec.stats()["verify"]["misses"] == 1
        assert spec.draft.compile_stats()["decode"]["misses"] == 1
    finally:
        sched.close()
        spec.close()
        eng.close()


# ---------------------------------------------------------------------------
# (d) satellites: kvcache stats, fleet lane policy, bench_diff directions
# ---------------------------------------------------------------------------

def test_kvcache_hwm_and_fragmentation_stats():
    from mxnet_tpu import profiler
    c = SlotKVCache(num_slots=4, num_layers=1, max_seq=32, num_heads=2,
                    head_dim=4, name="hwmcache")
    try:
        a, b = c.acquire(), c.acquire()
        c.set_length(a, 10)
        c.set_length(b, 6)
        st = c.stats()
        assert st["hwm"] == 16 and st["slots_peak"] == 2
        assert st["fragmentation"] == pytest.approx(1 - 16 / 64.0)
        c.release(a)
        st = c.stats()
        assert st["hwm"] == 16            # high-water mark survives release
        assert st["tokens_cached"] == 6
        c.advance([b])
        assert c.stats()["hwm"] == 16     # still below peak
        rows = profiler.get_aggregate_stats()
        assert "generation.kvcache.hwmcache.hwm" in rows
        assert "generation.kvcache.hwmcache.slots_peak" in rows
    finally:
        c.close()


def test_fleet_gen_lane_policy(tiny_lm):
    """A ModelVersion declared gen_lane='prefill' retires requests after
    the first token and publishes the prompt K/V; a decode lane on the
    SAME prefix cache admits with a hit — the disaggregation handoff."""
    from mxnet_tpu.serving.fleet import ModelRegistry
    pc = PrefixCache(block=4, name="lane.px")
    pre_eng = _engine(tiny_lm, chunk=4, prefix_cache=pc, name="lane.pre")
    dec_eng = _engine(tiny_lm, chunk=4, prefix_cache=pc, name="lane.dec")
    pre = GenerationScheduler(pre_eng, retry_policy=False, name="lane.pre")
    dec = GenerationScheduler(dec_eng, retry_policy=False, name="lane.dec")
    reg = ModelRegistry(name="lanereg")
    try:
        mv_pre = reg.load("lm", "prefill", generator=pre,
                          gen_lane="prefill")
        mv_dec = reg.load("lm", "decode", generator=dec, gen_lane="decode")
        assert mv_pre.health()["gen_lane"] == "prefill"
        assert mv_dec.health()["gen_lane"] == "decode"

        prompt = list(range(1, 14))
        req = pre.submit(prompt, max_new_tokens=16)
        toks = req.result(timeout=120)
        assert req.finish_reason == "prefill" and len(toks) == 1
        assert pre_eng.cache.in_use == 0          # slot released at once
        pre_eng.prefix_flush()   # the handoff barrier: publish landed
        assert pc.stats()["insertions"] >= 1
        assert pre.metrics.snapshot()["retired_prefill"] == 1

        got = dec.submit(prompt, max_new_tokens=4).result(timeout=120)
        assert got == _ref_greedy(tiny_lm, prompt, 4)
        assert dec.stats()["prefix_hits"] == 1
        assert dec.stats()["decode_lane_misses"] == 0
        assert toks[0] == got[0]                  # same first token
    finally:
        reg.close()
        pc.close()


def test_scheduler_lane_validation(tiny_lm):
    eng = _engine(tiny_lm, prefix_cache=False, name="lane.bad")
    try:
        with pytest.raises(ServingError):
            GenerationScheduler(eng, retry_policy=False,
                                lane_policy="bogus")
        s = GenerationScheduler(eng, retry_policy=False)
        assert s.lane_policy == "mixed"
        s.set_lane_policy("decode")
        assert s.stats()["lane"] == "decode"
        s.close()
    finally:
        eng.close()


def test_bench_diff_generation_directions(tmp_path):
    """The GENERATION.json v2 fields gate correctly: tokens/s up-is-good,
    TTFT/inter-token down-is-good, hit/acceptance rates informational."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.bench_diff import diff, direction_for, HIGHER, LOWER, INFO

    assert direction_for("prefix_cache.warm_tokens_s") == HIGHER
    assert direction_for("prefix_cache.tokens_saved") == HIGHER
    assert direction_for("prefix_cache.hit_rate") == INFO
    assert direction_for("speculative.acceptance_rate") == INFO
    assert direction_for("chunked_prefill.chunked.inter_token_p99_ms") \
        == LOWER
    assert direction_for("continuous.ttft_ms.p99") == LOWER

    base = {"prefix_cache": {"warm_tokens_s": 100.0, "hit_rate": 1.0},
            "chunked_prefill": {"chunked": {"inter_token_p99_ms": 10.0}}}
    # hit_rate halves (workload mix) but nothing gated regresses
    cand = {"prefix_cache": {"warm_tokens_s": 101.0, "hit_rate": 0.5},
            "chunked_prefill": {"chunked": {"inter_token_p99_ms": 9.0}}}
    verdict = diff(base, cand)
    assert verdict["status"] == "ok"
    assert any(d["metric"] == "prefix_cache.hit_rate"
               for d in verdict["drifts"])
    # a real regression still gates
    cand["chunked_prefill"]["chunked"]["inter_token_p99_ms"] = 20.0
    assert diff(base, cand)["status"] == "regression"


def test_bench_regression_gate_vs_pr7_artifact():
    """CI check: the committed v2 GENERATION.json must not regress the
    committed PR 7 artifact on any shared gated metric (tools/bench_diff
    --gate contract; exit 2 = regression)."""
    from tools.bench_diff import load_artifact, diff
    root = os.path.join(os.path.dirname(__file__), "..", "benchmark")
    pr7 = load_artifact(os.path.join(root, "GENERATION_pr7.json"))
    cur = load_artifact(os.path.join(root, "GENERATION.json"))
    verdict = diff(pr7, cur, tolerance=0.25)  # CPU-oracle noise floor
    assert verdict["compared"] > 0
    assert verdict["status"] == "ok", verdict["regressions"]
    # and the v2 acceptance flags are recorded true in the artifact
    assert cur["prefix_cache"]["outputs_bitwise_equal"] is True
    assert cur["prefix_cache"]["prefill_tokens_skipped_pct"] >= 0.90
    assert cur["speculative"]["token_exact"] is True
    assert cur["decode_compile_misses"] == 1
    assert cur["chunked_prefill"]["chunked"]["inter_token_p99_ms"] < \
        cur["chunked_prefill"]["monolithic"]["inter_token_p99_ms"]


def test_flash_attention_knob(monkeypatch):
    """MXNET_FLASH_ATTENTION=0 (and the legacy MXTPU_DISABLE_FLASH)
    disable the pallas flash dispatch — the with/without switch
    benchmark/bench_lm.py's bertdelta records the BERT MFU delta with."""
    from mxnet_tpu.ops.nn import _flash_enabled
    monkeypatch.delenv("MXTPU_DISABLE_FLASH", raising=False)
    monkeypatch.delenv("MXNET_FLASH_ATTENTION", raising=False)
    assert _flash_enabled()                      # default on
    monkeypatch.setenv("MXNET_FLASH_ATTENTION", "0")
    assert not _flash_enabled()
    monkeypatch.setenv("MXNET_FLASH_ATTENTION", "1")
    assert _flash_enabled()
    monkeypatch.setenv("MXTPU_DISABLE_FLASH", "1")
    assert not _flash_enabled()                  # legacy override wins


def test_generation_gauge_includes_prefix(tiny_lm):
    from mxnet_tpu.serving import generation as gen
    pc = PrefixCache(block=4, name="gauge.px")
    eng = _engine(tiny_lm, chunk=4, prefix_cache=pc, name="gauge.px")
    sched = GenerationScheduler(eng, retry_policy=False, name="gauge.px")
    try:
        sched.submit(list(range(1, 10)), max_new_tokens=2).result(
            timeout=120)
        eng.prefix_flush()
        g = gen.gauge()
        assert "gauge.px" in g["prefix"]
        assert g["prefix"]["gauge.px"]["insertions"] >= 1
    finally:
        sched.close()
        eng.close()
