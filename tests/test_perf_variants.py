"""Oracle tests for the gated round-5 perf-experiment paths.

These paths are OFF by default (each measured as an end-to-end loss on
the chip — see PERF.md round-5 study) but stay in the tree behind env
knobs for future XLA versions; these tests pin their correctness against
the default lowerings.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from mxnet_tpu.ops import nn as opsnn


def _direct_conv(x, w, s, p):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(x, w, (s, s), [(p, p), (p, p)],
                                    dimension_numbers=dn)


@pytest.mark.parametrize("C,O,K,p,H", [(3, 64, 7, 3, 224), (3, 16, 7, 3, 32),
                                       (4, 8, 5, 2, 20), (1, 8, 5, 1, 16)])
def test_conv_s2d_matches_direct(C, O, K, p, H):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, C, H, H).astype(np.float32))
    w = jnp.asarray(rng.randn(O, C, K, K).astype(np.float32))
    a = opsnn._conv_s2d_stride2(x, w, [(p, p), (p, p)])
    b = _direct_conv(x, w, 2, p)
    assert a.shape == b.shape
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-4, atol=1e-4)
    g = jnp.asarray(rng.randn(*np.array(a.shape)).astype(np.float32))
    ga = jax.grad(lambda w: (opsnn._conv_s2d_stride2(
        x, w, [(p, p), (p, p)]) * g).sum())(w)
    gb = jax.grad(lambda w: (_direct_conv(x, w, 2, p) * g).sum())(w)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               rtol=1e-4, atol=1e-4)


def _ref_pool(x, k, s, p):
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 1) + k, (1, 1) + s,
                             [(0, 0), (0, 0)] + [(pp, pp) for pp in p])


@pytest.mark.parametrize("k,s,p,H", [((3, 3), (2, 2), (1, 1), 28),
                                     ((2, 2), (2, 2), (0, 0), 28),
                                     ((3, 3), (2, 2), (0, 0), 27),
                                     ((2, 2), (1, 1), (0, 0), 9),
                                     ((3, 3), (3, 3), (1, 1), 13),
                                     # k < s: inter-window gaps must get
                                     # zero gradient
                                     ((2, 2), (3, 3), (0, 0), 9),
                                     ((1, 1), (2, 2), (1, 1), 8)])
def test_maxpool_eqbwd_matches_select_and_scatter(k, s, p, H):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 3, H, H).astype(np.float32))
    pd = [(p[0], p[0]), (p[1], p[1])]
    o_new = opsnn._maxpool2d_nchw(x, k, s, pd)
    o_ref = _ref_pool(x, k, s, p)
    np.testing.assert_allclose(np.asarray(o_new), np.asarray(o_ref))
    g = jnp.asarray(rng.randn(*np.array(o_ref.shape)).astype(np.float32))
    gr_new = jax.grad(lambda x: (opsnn._maxpool2d_nchw(
        x, k, s, pd) * g).sum())(x)
    gr_ref = jax.grad(lambda x: (_ref_pool(x, k, s, p) * g).sum())(x)
    # random floats: no ties, so tie-splitting == first-max exactly
    np.testing.assert_allclose(np.asarray(gr_new), np.asarray(gr_ref),
                               atol=1e-5)


def test_maxpool_eqbwd_tie_mass_preserved():
    # all-equal input: every window's gradient mass lands exactly once
    x = jnp.zeros((1, 1, 6, 6))
    gr = jax.grad(lambda x: opsnn._maxpool2d_nchw(
        x, (3, 3), (2, 2), [(1, 1), (1, 1)]).sum())(x)
    np.testing.assert_allclose(float(np.asarray(gr).sum()), 9.0, rtol=1e-6)


def test_hash_dropout_mask_statistics():
    k = jax.random.PRNGKey(42)
    for keep in (0.5, 0.7, 0.9):
        m = np.asarray(opsnn._hash_keep_mask(k, (64, 128, 768), keep))
        assert abs(m.mean() - keep) < 2e-3
        flat = m.reshape(-1).astype(np.float64)
        corr = np.corrcoef(flat[:-1], flat[1:])[0, 1]
        assert abs(corr) < 3e-3
    # distinct keys decorrelate
    m1 = np.asarray(opsnn._hash_keep_mask(jax.random.PRNGKey(1), (4096,), .5))
    m2 = np.asarray(opsnn._hash_keep_mask(jax.random.PRNGKey(2), (4096,), .5))
    assert 0.4 < (m1 == m2).mean() < 0.6


def test_bert_gather_first_mlm_matches_full_decode():
    """Gather-first decode must produce exactly the logits the full-seq
    path gathers afterwards."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.bert import bert_tiny
    np.random.seed(0)
    mx.random.seed(0)
    net = bert_tiny(vocab_size=50, max_length=16)
    net.initialize(mx.init.Xavier())
    B, T, M = 2, 16, 4
    tokens = mx.nd.array(np.random.randint(4, 50, (B, T)).astype("float32"))
    segments = mx.nd.zeros((B, T))
    pos = np.stack([np.random.choice(T, M, replace=False)
                    for _ in range(B)]).astype("float32")
    positions = mx.nd.array(pos)
    _, _, full, _ = net(tokens, segments, None)
    _, _, picked, _ = net(tokens, segments, None, positions)
    want = np.take_along_axis(full.asnumpy(),
                              pos.astype(int)[:, :, None], axis=1)
    np.testing.assert_allclose(picked.asnumpy(), want, rtol=1e-5, atol=1e-5)


def test_fwd_barrier_identity_gradient():
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    y, vjp = jax.vjp(opsnn._fwd_barrier, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    g = jnp.ones_like(x)
    np.testing.assert_allclose(np.asarray(vjp(g)[0]), np.asarray(g))
