"""Second block of reference operator-corpus ports (VERDICT r3 item 6,
`tests/python/unittest/test_operator.py`): indexing/gather/scatter,
topk/sort family, sequence ops, normalization layers, activation family,
embedding, dropout statistics — all against in-file numpy oracles."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, nd

rng = onp.random.RandomState(13)


def _a(*shape, scale=1.0):
    return (rng.standard_normal(shape) * scale).astype("float32")


# -------------------------------------------------------- indexing families

def test_take_modes():
    x = _a(5, 3)
    idx = onp.array([0, 4, 2], "float32")
    onp.testing.assert_allclose(
        mx.nd.take(nd.array(x), nd.array(idx)).asnumpy(), x[[0, 4, 2]])
    # clip mode on out-of-range
    idx_oob = onp.array([-1, 7], "float32")
    out = mx.nd.take(nd.array(x), nd.array(idx_oob), mode="clip").asnumpy()
    onp.testing.assert_allclose(out, x[[0, 4]])
    # wrap mode
    out = mx.nd.take(nd.array(x), nd.array(idx_oob), mode="wrap").asnumpy()
    onp.testing.assert_allclose(out, x[[-1 % 5, 7 % 5]])
    # axis=1
    out = mx.nd.take(nd.array(x), nd.array(onp.array([2, 0], "float32")),
                     axis=1).asnumpy()
    onp.testing.assert_allclose(out, x[:, [2, 0]])


def test_gather_scatter_nd_roundtrip():
    x = _a(3, 4)
    indices = onp.array([[0, 2, 1], [1, 3, 0]], "float32")  # (2, N)
    got = mx.nd.gather_nd(nd.array(x), nd.array(indices)).asnumpy()
    onp.testing.assert_allclose(got, x[[0, 2, 1], [1, 3, 0]])
    # scatter the gathered values back into zeros: recovers those cells
    scat = mx.nd.scatter_nd(nd.array(got), nd.array(indices),
                            shape=(3, 4)).asnumpy()
    expect = onp.zeros((3, 4), "float32")
    expect[[0, 2, 1], [1, 3, 0]] = x[[0, 2, 1], [1, 3, 0]]
    onp.testing.assert_allclose(scat, expect)


def test_one_hot_and_embedding_grad():
    idx = onp.array([1, 0, 3], "float32")
    oh = mx.nd.one_hot(nd.array(idx), depth=4, on_value=2.0,
                       off_value=-1.0).asnumpy()
    expect = onp.full((3, 4), -1.0, "float32")
    expect[onp.arange(3), idx.astype(int)] = 2.0
    onp.testing.assert_allclose(oh, expect)

    # Embedding backward: counts of each index land in the weight grad
    w = nd.array(_a(5, 4))
    w.attach_grad()
    ids = nd.array(onp.array([1, 1, 2], "float32"))
    with ag.record():
        y = mx.nd.Embedding(ids, w, input_dim=5, output_dim=4).sum()
    y.backward()
    g = w.grad.asnumpy()
    onp.testing.assert_allclose(g[1], onp.full(4, 2.0), rtol=1e-6)
    onp.testing.assert_allclose(g[2], onp.full(4, 1.0), rtol=1e-6)
    onp.testing.assert_allclose(g[0], onp.zeros(4), rtol=1e-6)


# ----------------------------------------------------------- topk/sort/argmax

def test_topk_modes():
    x = _a(2, 6)
    # ret_typ='indices' (default) returns the positions of the k largest
    out = mx.nd.topk(nd.array(x), k=2, axis=1).asnumpy()
    expect = onp.argsort(-x, axis=1)[:, :2]
    onp.testing.assert_allclose(out, expect.astype("float32"))
    # value mode
    vals = mx.nd.topk(nd.array(x), k=2, axis=1, ret_typ="value").asnumpy()
    onp.testing.assert_allclose(vals, -onp.sort(-x, axis=1)[:, :2],
                                rtol=1e-6)
    # smallest
    vals = mx.nd.topk(nd.array(x), k=2, axis=1, ret_typ="value",
                      is_ascend=True).asnumpy()
    onp.testing.assert_allclose(vals, onp.sort(x, axis=1)[:, :2],
                                rtol=1e-6)


def test_sort_argsort_argmax():
    x = _a(3, 5)
    onp.testing.assert_allclose(
        mx.nd.sort(nd.array(x), axis=1).asnumpy(), onp.sort(x, 1))
    onp.testing.assert_allclose(
        mx.nd.sort(nd.array(x), axis=1, is_ascend=False).asnumpy(),
        -onp.sort(-x, 1))
    onp.testing.assert_allclose(
        mx.nd.argsort(nd.array(x), axis=1).asnumpy(),
        onp.argsort(x, 1).astype("float32"))
    onp.testing.assert_allclose(
        mx.nd.argmax(nd.array(x), axis=1).asnumpy(),
        onp.argmax(x, 1).astype("float32"))
    onp.testing.assert_allclose(
        mx.nd.argmin(nd.array(x), axis=0).asnumpy(),
        onp.argmin(x, 0).astype("float32"))


# -------------------------------------------------------------- sequence ops

def test_sequence_mask_last_reverse():
    # layout (T, N, C) with per-batch lengths — reference SequenceMask
    T, N, C = 5, 3, 2
    x = _a(T, N, C)
    lens = onp.array([2, 5, 3], "float32")
    masked = mx.nd.SequenceMask(nd.array(x), nd.array(lens),
                                use_sequence_length=True,
                                value=-7.0).asnumpy()
    expect = x.copy()
    for n, l in enumerate(lens.astype(int)):
        expect[l:, n, :] = -7.0
    onp.testing.assert_allclose(masked, expect)

    last = mx.nd.SequenceLast(nd.array(x), nd.array(lens),
                              use_sequence_length=True).asnumpy()
    expect_last = onp.stack([x[int(l) - 1, n] for n, l in enumerate(lens)])
    onp.testing.assert_allclose(last, expect_last)

    rev = mx.nd.SequenceReverse(nd.array(x), nd.array(lens),
                                use_sequence_length=True).asnumpy()
    expect_rev = x.copy()
    for n, l in enumerate(lens.astype(int)):
        expect_rev[:l, n, :] = x[:l, n, :][::-1]
    onp.testing.assert_allclose(rev, expect_rev)


# -------------------------------------------------------------- norm layers

def test_layernorm_oracle():
    x = _a(4, 6)
    gamma = onp.abs(_a(6)) + 0.5
    beta = _a(6)
    out = mx.nd.LayerNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                          axis=-1, eps=1e-5).asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / onp.sqrt(var + 1e-5) * gamma + beta
    onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_instancenorm_oracle():
    x = _a(2, 3, 4, 4)
    gamma = onp.ones(3, "float32")
    beta = onp.zeros(3, "float32")
    out = mx.nd.InstanceNorm(nd.array(x), nd.array(gamma),
                             nd.array(beta), eps=1e-5).asnumpy()
    mu = x.mean(axis=(2, 3), keepdims=True)
    var = x.var(axis=(2, 3), keepdims=True)
    onp.testing.assert_allclose(out, (x - mu) / onp.sqrt(var + 1e-5),
                                rtol=1e-4, atol=1e-5)


def test_l2normalization_modes():
    x = _a(2, 3, 4)
    out = mx.nd.L2Normalization(nd.array(x), mode="instance").asnumpy()
    norm = onp.sqrt((x.reshape(2, -1) ** 2).sum(1) + 1e-10)
    onp.testing.assert_allclose(out, x / norm.reshape(2, 1, 1),
                                rtol=1e-5)
    out = mx.nd.L2Normalization(nd.array(x), mode="channel").asnumpy()
    norm = onp.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10)
    onp.testing.assert_allclose(out, x / norm, rtol=1e-5)


def test_lrn_oracle():
    x = onp.abs(_a(1, 5, 3, 3)) + 0.1
    nsize, alpha, beta, knorm = 3, 1e-4, 0.75, 2.0
    out = mx.nd.LRN(nd.array(x), nsize=nsize, alpha=alpha, beta=beta,
                    knorm=knorm).asnumpy()
    C = x.shape[1]
    ref = onp.zeros_like(x)
    half = nsize // 2
    for c in range(C):
        lo, hi = max(0, c - half), min(C, c + half + 1)
        sq = (x[:, lo:hi] ** 2).sum(1)
        ref[:, c] = x[:, c] / ((knorm + alpha * sq / nsize) ** beta)
    onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


# --------------------------------------------------------- activation family

def test_activation_family_oracles():
    x = _a(3, 4) * 2
    checks = {
        "softsign": x / (1 + onp.abs(x)),
        "softrelu": onp.log1p(onp.exp(x)),
    }
    for act, ref in checks.items():
        out = mx.nd.Activation(nd.array(x), act_type=act).asnumpy()
        onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5,
                                    err_msg=act)
    onp.testing.assert_allclose(
        mx.nd.hard_sigmoid(nd.array(x)).asnumpy(),
        onp.clip(0.2 * x + 0.5, 0, 1), rtol=1e-5)
    onp.testing.assert_allclose(
        mx.nd.LeakyReLU(nd.array(x), act_type="leaky",
                        slope=0.1).asnumpy(),
        onp.where(x > 0, x, 0.1 * x), rtol=1e-5)
    # elu
    onp.testing.assert_allclose(
        mx.nd.LeakyReLU(nd.array(x), act_type="elu", slope=1.0).asnumpy(),
        onp.where(x > 0, x, onp.expm1(x)), rtol=1e-4, atol=1e-5)
    # log_softmax rows sum to ~1 in exp space
    ls = mx.nd.log_softmax(nd.array(x), axis=-1).asnumpy()
    onp.testing.assert_allclose(onp.exp(ls).sum(-1), onp.ones(3),
                                rtol=1e-5)


def test_prelu_learned_slope_grad():
    x = nd.array(onp.array([[-2.0, 3.0]], "float32"))
    gamma = nd.array(onp.array([0.25], "float32"))
    gamma.attach_grad()
    with ag.record():
        y = mx.nd.LeakyReLU(x, gamma, act_type="prelu")
        s = y.sum()
    s.backward()
    # d/dgamma = sum of negative inputs = -2
    onp.testing.assert_allclose(gamma.grad.asnumpy(), [-2.0], rtol=1e-5)


# ------------------------------------------------------------------- dropout

def test_dropout_statistics_and_modes():
    x = nd.array(onp.ones((200, 200), "float32"))
    # inference: identity
    out = mx.nd.Dropout(x, p=0.5).asnumpy()
    onp.testing.assert_allclose(out, 1.0)
    # training: ~p zeros, survivors scaled 1/(1-p)
    with ag.record():
        out = mx.nd.Dropout(x, p=0.5).asnumpy()
    frac_zero = float((out == 0).mean())
    assert 0.45 < frac_zero < 0.55, frac_zero
    kept = out[out != 0]
    onp.testing.assert_allclose(kept, 2.0, rtol=1e-5)
    # mode='always' applies at inference too
    out = mx.nd.Dropout(x, p=0.5, mode="always").asnumpy()
    assert 0.4 < float((out == 0).mean()) < 0.6


# ----------------------------------------------------------------- where/clip

def test_where_clip_maximum_scalar():
    x = _a(3, 4)
    cond = (x > 0).astype("float32")
    y = _a(3, 4)
    out = mx.nd.where(nd.array(cond), nd.array(x), nd.array(y)).asnumpy()
    onp.testing.assert_allclose(out, onp.where(cond > 0, x, y))
    onp.testing.assert_allclose(
        mx.nd.clip(nd.array(x), -0.5, 0.5).asnumpy(),
        onp.clip(x, -0.5, 0.5))
    onp.testing.assert_allclose(
        (mx.nd.maximum(nd.array(x), 0.1)).asnumpy(),
        onp.maximum(x, 0.1))
