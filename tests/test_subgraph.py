"""Subgraph partitioning tests (reference `tests/python/unittest/
test_subgraph_op.py` semantics over the TPU-native partitioner)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.symbol import subgraph


def _mlp():
    x = sym.var("data")
    w = sym.var("w")
    h = sym.FullyConnected(x, w, no_bias=True, num_hidden=4, name="fc")
    a = sym.relu(h + 1.0)
    b = sym.tanh(a * 2.0)
    return b


def _bindings():
    rng = onp.random.default_rng(0)
    return {"data": nd.array(rng.random((2, 3)).astype("float32")),
            "w": nd.array(rng.random((4, 3)).astype("float32"))}


def test_partition_preserves_semantics():
    net = _mlp()
    vals = _bindings()
    ex = net.bind(mx.cpu(), dict(vals))
    want = ex.forward()[0].asnumpy()
    fused = net.get_backend_symbol("TPU_ELEMWISE")
    ex2 = fused.bind(mx.cpu(), dict(vals))
    got = ex2.forward()[0].asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5)


def test_partition_actually_fuses():
    net = _mlp()
    fused = net.get_backend_symbol("TPU_ELEMWISE")
    nodes = fused._toposort()
    sub = [n for n in nodes if n._attr.get("__subgraph__")]
    assert len(sub) >= 1
    # the elementwise chain (add/relu/mul/tanh) collapsed into the region
    ops = sub[0]._attr["__subgraph_ops__"].split(",")
    assert len(ops) >= 3
    # FullyConnected stays outside
    assert all("FullyConnected" not in o for o in ops)
    outside = [n for n in nodes if n._op is not None
               and not n._attr.get("__subgraph__")]
    assert any(n._op.name == "FullyConnected" for n in outside)


def test_partition_backward_matches():
    net = _mlp()
    vals = _bindings()
    grads = {k: nd.zeros(v.shape) for k, v in vals.items()}
    ex = net.bind(mx.cpu(), dict(vals), args_grad=dict(grads))
    out = ex.forward(is_train=True)[0]
    ex.backward(nd.ones(out.shape))
    want = {k: g.asnumpy().copy() for k, g in ex.grad_dict.items()}

    fused = net.get_backend_symbol("TPU_ELEMWISE")
    grads2 = {k: nd.zeros(v.shape) for k, v in vals.items()}
    ex2 = fused.bind(mx.cpu(), dict(vals), args_grad=dict(grads2))
    out2 = ex2.forward(is_train=True)[0]
    ex2.backward(nd.ones(out2.shape))
    for k in want:
        onp.testing.assert_allclose(ex2.grad_dict[k].asnumpy(), want[k],
                                    rtol=1e-5)


def test_env_knob_applies_at_bind(monkeypatch):
    monkeypatch.setenv("MXNET_SUBGRAPH_BACKEND", "TPU_ELEMWISE")
    net = _mlp()
    vals = _bindings()
    ex = net.bind(mx.cpu(), dict(vals))
    sub = [n for n in ex._symbol._toposort()
           if n._attr.get("__subgraph__")]
    assert sub, "bind should partition when MXNET_SUBGRAPH_BACKEND is set"
    want = net.bind(mx.cpu(), dict(vals))  # still partitioned, fine
    onp.testing.assert_allclose(ex.forward()[0].asnumpy(),
                                want.forward()[0].asnumpy(), rtol=1e-6)


def test_custom_property_registration():
    class EverythingSelector(subgraph.SubgraphSelector):
        def select(self, node):
            return True

        def min_size(self):
            return 1

    class WholeGraphProperty(subgraph.SubgraphProperty):
        name = "TEST_ALL"

        def create_selector(self):
            return EverythingSelector()

    subgraph.register_subgraph_property("TEST_ALL", WholeGraphProperty())
    assert "TEST_ALL" in subgraph.list_backends()
    net = _mlp()
    fused = net.get_backend_symbol("TEST_ALL")
    nodes = [n for n in fused._toposort() if n._op is not None]
    # entire compute graph collapsed into one fused node
    assert len(nodes) == 1
    assert nodes[0]._attr.get("__subgraph__") == "TEST_ALL"
    vals = _bindings()
    got = fused.bind(mx.cpu(), dict(vals)).forward()[0].asnumpy()
    want = net.bind(mx.cpu(), dict(vals)).forward()[0].asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5)


def test_unknown_backend_raises():
    with pytest.raises(ValueError):
        _mlp().get_backend_symbol("NOPE")


def test_binary_elemwise_fuses():
    x = sym.var("a")
    y = sym.var("b")
    out = sym.tanh(sym.elemwise_add(sym.relu(x), sym.relu(y)))
    fused = out.get_backend_symbol("TPU_ELEMWISE")
    subs = [n for n in fused._toposort() if n._attr.get("__subgraph__")]
    assert len(subs) == 1
    assert len(subs[0]._attr["__subgraph_ops__"].split(",")) == 4
    vals = {"a": nd.array(onp.array([[-1.0, 2.0]], "float32")),
            "b": nd.array(onp.array([[3.0, -4.0]], "float32"))}
    got = fused.bind(mx.cpu(), dict(vals)).forward()[0].asnumpy()
    want = out.bind(mx.cpu(), dict(vals)).forward()[0].asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_non_convex_region_is_cut():
    # a -> FC -> FC -> d  and  a -> d : a,d both selectable but the path
    # through the two FCs leaves the region — partitioner must not fuse
    # {a, d} together (reference build_subgraph.cc convexity labelling)
    x = sym.var("data")
    a = sym.relu(x)
    c = sym.FullyConnected(sym.FullyConnected(a, num_hidden=2, name="fc1"),
                           num_hidden=2, name="fc2")
    d = sym.elemwise_add(a * 1.0, c)
    fused = d.get_backend_symbol("TPU_ELEMWISE")  # must not crash
    vals = {"data": nd.array(onp.ones((2, 2), "float32"))}
    ex = fused.simple_bind(mx.cpu(), data=(2, 2))
    for k in ex.arg_dict:
        ex.arg_dict[k][:] = 0.5
    ex0 = d.simple_bind(mx.cpu(), data=(2, 2))
    for k in ex0.arg_dict:
        ex0.arg_dict[k][:] = 0.5
    onp.testing.assert_allclose(ex.forward()[0].asnumpy(),
                                ex0.forward()[0].asnumpy(), rtol=1e-5)


def test_partitioned_symbol_json_roundtrip():
    from mxnet_tpu.symbol import load_json
    net = _mlp()
    fused = net.get_backend_symbol("TPU_ELEMWISE")
    js = fused.tojson()
    back = load_json(js)
    vals = _bindings()
    got = back.bind(mx.cpu(), dict(vals)).forward()[0].asnumpy()
    want = net.bind(mx.cpu(), dict(vals)).forward()[0].asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5)


def test_env_knob_positional_args_keep_original_order(monkeypatch):
    # reviewer repro: partitioning permutes list_arguments traversal; a
    # positional-args bind must still map by the ORIGINAL symbol's order
    monkeypatch.setenv("MXNET_SUBGRAPH_BACKEND", "TPU_ELEMWISE")
    a = sym.var("a")
    c = sym.var("c")
    out = sym.elemwise_add(
        sym.FullyConnected(a, num_hidden=2, no_bias=True, name="fc"),
        sym.relu(c))
    names = out.list_arguments()
    vals = {"a": nd.array(onp.ones((1, 2), "float32")),
            "fc_weight": nd.array(onp.ones((2, 2), "float32")),
            "c": nd.array(onp.zeros((1, 2), "float32"))}
    ex = out.bind(mx.cpu(), [vals[n] for n in names])
    onp.testing.assert_allclose(ex.forward()[0].asnumpy(), [[2.0, 2.0]])


def test_env_knob_rebind_reuses_fused_ops(monkeypatch):
    monkeypatch.setenv("MXNET_SUBGRAPH_BACKEND", "TPU_ELEMWISE")
    from mxnet_tpu.ops.registry import list_ops
    net = _mlp()
    vals = _bindings()
    net.bind(mx.cpu(), dict(vals)).forward()
    n_ops_after_first = len(list_ops())
    for _ in range(3):
        net.bind(mx.cpu(), dict(vals)).forward()
    assert len(list_ops()) == n_ops_after_first


def test_group2ctx_places_ops_on_devices():
    # reference model-parallel placement (symbol.py:1505 group2ctx,
    # graph_executor.cc:1956): each group's ops run on its device, with
    # cross-device transfers at boundaries. The test conftest provides 8
    # virtual CPU devices addressable as mx.cpu(i).
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    with sym.AttrScope(ctx_group="dev1"):
        a = sym.var("a")
        h = sym.relu(a)
    with sym.AttrScope(ctx_group="dev2"):
        out = sym.tanh(h * 2.0)
    ex = out.bind(mx.cpu(0),
                  {"a": nd.array(onp.array([-1.0, 1.0], "float32"))},
                  group2ctx={"dev1": mx.cpu(1), "dev2": mx.cpu(2)})
    got = ex.forward()[0].asnumpy()
    onp.testing.assert_allclose(got, onp.tanh(2 * onp.maximum([-1, 1], 0)),
                                rtol=1e-6)
    # placement map resolved to distinct devices
    devs = set(ex._placement.values())
    assert len(devs) == 2


def test_group2ctx_backward_crosses_devices():
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    with sym.AttrScope(ctx_group="dev1"):
        a = sym.var("a")
        h = sym.FullyConnected(a, num_hidden=3, no_bias=True, name="fc")
    with sym.AttrScope(ctx_group="dev2"):
        out = sym.sum(sym.tanh(h))
    vals = {"a": nd.array(onp.ones((2, 2), "float32")),
            "fc_weight": nd.array(0.1 * onp.ones((3, 2), "float32"))}
    grads = {k: nd.zeros(v.shape) for k, v in vals.items()}
    ex = out.bind(mx.cpu(0), dict(vals), args_grad=grads,
                  group2ctx={"dev1": mx.cpu(3), "dev2": mx.cpu(4)})
    ex.forward(is_train=True)
    ex.backward()
    g = grads["fc_weight"].asnumpy()
    assert g.shape == (3, 2) and onp.abs(g).sum() > 0


def test_attrscope_reentrant_and_reusable():
    s = sym.AttrScope(ctx_group="g")
    with s:
        with s:
            pass
    assert sym.AttrScope.current_attrs() == {}
    v = sym.var("after_scope")
    assert v.attr("ctx_group") is None
    # reuse after nesting inside another scope must not leak outer attrs
    with sym.AttrScope(lr_mult="2"):
        with s:
            pass
    with s:
        v2 = sym.var("only_group")
    assert v2.attr("ctx_group") == "g"
    assert v2.attr("lr_mult") is None


def test_fused_region_keeps_ctx_group(monkeypatch):
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    monkeypatch.setenv("MXNET_SUBGRAPH_BACKEND", "TPU_ELEMWISE")
    with sym.AttrScope(ctx_group="dev1"):
        a = sym.var("a")
        out = sym.tanh(sym.relu(a) * 2.0)
    ex = out.bind(mx.cpu(0), {"a": nd.array(onp.ones((2,), "float32"))},
                  group2ctx={"dev1": mx.cpu(1)})
    assert ex._placement, "fused node must inherit the region's ctx_group"
    onp.testing.assert_allclose(ex.forward()[0].asnumpy(),
                                onp.tanh([2.0, 2.0]), rtol=1e-6)


def test_fusion_respects_group_barrier(monkeypatch):
    # ops in different ctx_groups must never fuse into one region
    monkeypatch.setenv("MXNET_SUBGRAPH_BACKEND", "TPU_ELEMWISE")
    with sym.AttrScope(ctx_group="dev1"):
        a = sym.var("a")
        h = sym.relu(a) * 2.0
    with sym.AttrScope(ctx_group="dev2"):
        out = sym.tanh(sym.exp(h))
    fused = out.get_backend_symbol("TPU_ELEMWISE")
    subs = [n for n in fused._toposort() if n._attr.get("__subgraph__")]
    groups = {s._attr.get("ctx_group") for s in subs}
    assert None not in groups
    # two regions, one per group
    assert {s._attr["ctx_group"] for s in subs} == {"dev1", "dev2"}


def test_simple_bind_and_module_forward_group2ctx():
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    with sym.AttrScope(ctx_group="g1"):
        x = sym.var("data")
        out = sym.FullyConnected(x, num_hidden=3, name="fcg")
    ex = out.simple_bind(mx.cpu(0), data=(2, 4),
                         group2ctx={"g1": mx.cpu(5)})
    assert ex._placement, "simple_bind must forward group2ctx"
    ex2 = ex.reshape(data=(4, 4))
    assert ex2._placement, "reshape must carry group2ctx"
    from mxnet_tpu.module import Module
    m = Module(out, data_names=("data",), label_names=None,
               group2ctxs={"g1": mx.cpu(6)})
    m.bind(data_shapes=[("data", (2, 4))], for_training=False)
    assert m._exec._placement, "Module must forward group2ctxs"
