"""RPN/SSD/deformable op family — semantics from reference
`src/operator/contrib/{multibox_target,multibox_detection,proposal,
multi_proposal,psroi_pooling,deformable_convolution,rroi_align}` and the
cases in `tests/python/unittest/test_operator.py` (test_multibox_*,
test_deformable_convolution)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag


def test_multibox_target_matches_obvious_assignment():
    # two anchors, one gt that overlaps anchor 0 heavily
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5], [0.6, 0.6, 0.9, 0.9]]],
                       "float32")
    label = np.array([[[2.0, 0.1, 0.1, 0.5, 0.5],
                       [-1.0, 0, 0, 0, 0]]], "float32")  # one padded row
    cls_pred = np.zeros((1, 4, 2), "float32")
    bt, bm, ct = mx.nd.contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred))
    ct = ct.asnumpy()
    assert ct[0, 0] == 3.0  # gt class 2 -> target 3 (background shifted)
    assert ct[0, 1] == 0.0  # unmatched -> background
    bm = bm.asnumpy().reshape(1, 2, 4)
    assert bm[0, 0].sum() == 4 and bm[0, 1].sum() == 0
    # perfectly-aligned anchor: offsets must be ~0
    bt = bt.asnumpy().reshape(1, 2, 4)
    np.testing.assert_allclose(bt[0, 0], 0.0, atol=1e-5)


def test_multibox_target_bipartite_forces_low_iou_match():
    # gt overlaps neither anchor above threshold; bipartite stage must still
    # claim the best anchor
    anchors = np.array([[[0.0, 0.0, 0.2, 0.2], [0.5, 0.5, 0.7, 0.7]]],
                       "float32")
    label = np.array([[[0.0, 0.45, 0.45, 0.65, 0.65]]], "float32")
    cls_pred = np.zeros((1, 2, 2), "float32")
    _, _, ct = mx.nd.contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred),
        overlap_threshold=0.9)
    np.testing.assert_array_equal(ct.asnumpy(), [[0.0, 1.0]])


def test_multibox_target_negative_mining():
    anchors = np.tile(np.array([[0.1, 0.1, 0.5, 0.5]], "float32"),
                      (6, 1))[None]
    anchors[0, 0] = [0.1, 0.1, 0.5, 0.5]
    anchors[0, 1:] = np.array([[0.6, 0.6, 0.9, 0.9]] * 5)
    label = np.array([[[1.0, 0.1, 0.1, 0.5, 0.5]]], "float32")
    cls_pred = np.zeros((1, 3, 6), "float32")
    cls_pred[0, 1, 2] = 5.0  # one confidently-wrong negative
    _, _, ct = mx.nd.contrib.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred),
        negative_mining_ratio=1.0, negative_mining_thresh=0.1,
        ignore_label=-1.0)
    ct = ct.asnumpy()[0]
    assert ct[0] == 2.0           # positive
    assert ct[2] == 0.0           # mined hard negative stays background
    assert (ct[3:] == -1.0).all()  # the rest ignored


def test_multibox_detection_decodes_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5], [0.12, 0.12, 0.52, 0.52],
                         [0.6, 0.6, 0.9, 0.9]]], "float32")
    cls_prob = np.zeros((1, 3, 3), "float32")
    cls_prob[0, :, 0] = [0.1, 0.8, 0.1]   # class 0
    cls_prob[0, :, 1] = [0.2, 0.7, 0.1]   # class 0, overlapping -> suppressed
    cls_prob[0, :, 2] = [0.1, 0.2, 0.7]   # class 1
    loc = np.zeros((1, 12), "float32")
    out = mx.nd.contrib.MultiBoxDetection(
        mx.nd.array(cls_prob), mx.nd.array(loc), mx.nd.array(anchors),
        nms_threshold=0.5).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    assert kept.shape[0] == 2
    ids = sorted(kept[:, 0].tolist())
    assert ids == [0.0, 1.0]
    best = kept[np.argmax(kept[:, 1])]
    np.testing.assert_allclose(best[2:6], [0.1, 0.1, 0.5, 0.5], atol=1e-5)


def test_proposal_shapes_and_clipping():
    rng = np.random.RandomState(0)
    A = 3 * 4  # ratios x scales
    H = W = 4
    cls = rng.rand(1, 2 * A, H, W).astype("float32")
    bbox = (rng.randn(1, 4 * A, H, W) * 0.1).astype("float32")
    im_info = np.array([[64.0, 64.0, 1.0]], "float32")
    (rois,) = mx.nd.contrib.Proposal(
        mx.nd.array(cls), mx.nd.array(bbox), mx.nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10, feature_stride=16)
    r = rois.asnumpy()
    assert r.shape == (10, 5)
    assert (r[:, 0] == 0).all()
    assert (r[:, 1:] >= 0).all() and (r[:, 1:] <= 63).all()
    live = r[r[:, 3] > r[:, 1]]
    assert live.shape[0] >= 1


def test_multi_proposal_batch_indices():
    rng = np.random.RandomState(1)
    A = 12
    cls = rng.rand(2, 2 * A, 3, 3).astype("float32")
    bbox = (rng.randn(2, 4 * A, 3, 3) * 0.1).astype("float32")
    im_info = np.tile([48.0, 48.0, 1.0], (2, 1)).astype("float32")
    rois, scores = mx.nd.contrib.MultiProposal(
        mx.nd.array(cls), mx.nd.array(bbox), mx.nd.array(im_info),
        rpn_pre_nms_top_n=40, rpn_post_nms_top_n=8, output_score=True)
    r = rois.asnumpy()
    assert r.shape == (16, 5) and scores.shape == (16, 1)
    assert (r[:8, 0] == 0).all() and (r[8:, 0] == 1).all()


def test_psroi_pooling_selects_bin_channels():
    # data where channel value == its bin index: output bin (i,j) must read
    # from channel group i*g+j
    g, cdim = 2, 3
    C = cdim * g * g
    data = np.zeros((1, C, 8, 8), "float32")
    for c in range(C):
        data[0, c] = c % (g * g)
    rois = np.array([[0, 0, 0, 7, 7]], "float32")
    out = mx.nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=1.0,
        output_dim=cdim, pooled_size=g).asnumpy()
    assert out.shape == (1, cdim, g, g)
    for i in range(g):
        for j in range(g):
            np.testing.assert_allclose(out[0, :, i, j], i * g + j,
                                       atol=1e-5)


def test_deformable_conv_zero_offset_matches_convolution():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 7, 7).astype("float32")
    w = rng.randn(4, 3, 3, 3).astype("float32")
    off = np.zeros((2, 2 * 9, 5, 5), "float32")
    out = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w), no_bias=True,
        kernel=(3, 3), num_filter=4).asnumpy()
    ref = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), no_bias=True,
                            kernel=(3, 3), num_filter=4).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_offset_shifts_sampling():
    x = np.zeros((1, 1, 5, 5), "float32")
    x[0, 0, 2, 3] = 1.0
    w = np.ones((1, 1, 1, 1), "float32")
    # offset (dy=0, dx=+1): 1x1 kernel reads one pixel to the right
    off = np.zeros((1, 2, 5, 5), "float32")
    off[0, 1] = 1.0
    out = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w), no_bias=True,
        kernel=(1, 1), num_filter=1).asnumpy()
    assert out[0, 0, 2, 2] == 1.0 and out[0, 0, 2, 3] == 0.0


def test_deformable_conv_offset_gradient_flows():
    rng = np.random.RandomState(3)
    x = mx.nd.array(rng.rand(1, 2, 6, 6).astype("float32"))
    w = mx.nd.array(rng.randn(3, 2, 3, 3).astype("float32"))
    off = mx.nd.array((rng.rand(1, 18, 4, 4) * 0.5).astype("float32"))
    off.attach_grad()
    with ag.record():
        out = mx.nd.contrib.DeformableConvolution(
            x, off, w, no_bias=True, kernel=(3, 3), num_filter=3)
        loss = (out * out).sum()
    loss.backward()
    assert np.abs(off.grad.asnumpy()).sum() > 0


def test_rroi_align_zero_angle_matches_axis_aligned():
    rng = np.random.RandomState(4)
    data = rng.rand(1, 2, 10, 10).astype("float32")
    # rotated roi centered at (5,5), w=h=6, angle 0
    rrois = np.array([[0, 5.0, 5.0, 6.0, 6.0, 0.0]], "float32")
    out0 = mx.nd.contrib.RROIAlign(mx.nd.array(data), mx.nd.array(rrois),
                                   pooled_size=(3, 3)).asnumpy()
    out90 = mx.nd.contrib.RROIAlign(
        mx.nd.array(data),
        mx.nd.array(np.array([[0, 5.0, 5.0, 6.0, 6.0, 90.0]], "float32")),
        pooled_size=(3, 3)).asnumpy()
    assert out0.shape == (1, 2, 3, 3)
    # a 90 degree rotation permutes the sampled grid, not its value set
    np.testing.assert_allclose(sorted(out0.ravel()), sorted(out90.ravel()),
                               atol=1e-4)


def test_deformable_psroi_pooling_no_trans():
    g, cdim = 2, 2
    C = cdim * g * g
    rng = np.random.RandomState(5)
    data = rng.rand(1, C, 8, 8).astype("float32")
    rois = np.array([[0, 1, 1, 7, 7]], "float32")
    (out,) = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), None, spatial_scale=1.0,
        output_dim=cdim, group_size=g, pooled_size=g, no_trans=True)
    assert out.shape == (1, cdim, g, g)
    assert np.isfinite(out.asnumpy()).all()


def test_psroi_pooling_group_not_equal_pooled():
    """group_size != pooled_size: output keeps the pooled grid and bin
    (i, j) reads channel group floor(i*g/p), floor(j*g/p) (regression:
    modulo tiling / group-sized output)."""
    g, p, cdim = 2, 4, 1
    C = cdim * g * g
    data = np.zeros((1, C, 8, 8), "float32")
    for c in range(C):
        data[0, c] = c
    rois = np.array([[0, 0, 0, 7, 7]], "float32")
    out = mx.nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=1.0,
        output_dim=cdim, pooled_size=p, group_size=g).asnumpy()
    assert out.shape == (1, cdim, p, p)
    ref = np.array([[0, 0, 1, 1], [0, 0, 1, 1],
                    [2, 2, 3, 3], [2, 2, 3, 3]], "float32")
    np.testing.assert_allclose(out[0, 0], ref, atol=1e-5)


def test_deformable_psroi_group_mapping():
    g, p, cdim = 2, 4, 1
    C = cdim * g * g
    data = np.zeros((1, C, 8, 8), "float32")
    for c in range(C):
        data[0, c] = c
    rois = np.array([[0, 0, 0, 8, 8]], "float32")
    (out,) = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), None, spatial_scale=1.0,
        output_dim=cdim, group_size=g, pooled_size=p, no_trans=True)
    ref = np.array([[0, 0, 1, 1], [0, 0, 1, 1],
                    [2, 2, 3, 3], [2, 2, 3, 3]], "float32")
    np.testing.assert_allclose(out.asnumpy()[0, 0], ref, atol=1e-5)
