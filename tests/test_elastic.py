"""Elastic, preemption-tolerant training — ISSUE-6 acceptance on the CPU
oracle.

Unit level: the host_loss/preempt chaos kinds, the SIGTERM grace-window
PreemptionHandler, the collective watchdog (hung all-reduce -> controlled
CollectiveTimeout abort, incl. the kvstore wiring), DeviceFeed.flush and
step_stream's chunk-boundary preemption, and the /healthz membership
gauge.

Process level (subprocess, real workers through `tools/launch.py`):

(a) a supervised 2-worker run that loses one worker to injected
    ``host_loss`` re-forms at world size 1 with MORE local devices
    (--total-devices re-spreads the pool: a genuine reshard), resumes
    from the rolling checkpoint, and finishes with a loss trajectory
    bitwise-equal to restore-and-replay from that same checkpoint;
(b) a REAL external SIGTERM produces an emergency checkpoint inside the
    grace window (worker exits EXIT_PREEMPTED), eviction, and a
    completed resumed run;
(c) the hardened plain launcher kills the remaining worker groups on the
    first hard failure and propagates per-worker exit codes;
(d) supervise mode honors the MXTPU_SSH shim (CI transport seam).
"""
import json
import os
import shutil
import signal
import stat
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.elastic import (CollectiveTimeout,
                                          CollectiveWatchdog,
                                          EXIT_PREEMPTED, Preempted,
                                          PreemptionHandler)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "launch.py")
WORKER = os.path.join(REPO, "tests", "dist", "elastic_worker.py")


@pytest.fixture(autouse=True)
def _disarm_chaos():
    from mxnet_tpu.resilience import elastic
    chaos.clear()
    elastic.clear_collective_alarm()
    yield
    chaos.clear()
    # watchdog tests latch the hung-collective /healthz alarm by design;
    # don't leak the degradation into unrelated tests
    elastic.clear_collective_alarm()


# ---------------------------------------------------------------------------
# chaos kinds: host_loss / preempt
# ---------------------------------------------------------------------------

def test_chaos_host_loss_kind(monkeypatch):
    """host_loss is deterministic and spec-grammar armable; the action
    (os._exit) is a monkeypatchable seam so the suite survives it."""
    died = []
    monkeypatch.setattr(chaos, "_host_loss_action",
                        lambda msg: died.append(msg))
    chaos.arm_from_env("hl.p:host_loss:at=2")
    chaos.point("hl.p")
    assert died == []
    chaos.point("hl.p")
    assert len(died) == 1 and "host_loss" in died[0]
    chaos.point("hl.p")
    assert len(died) == 1
    assert chaos.stats()["hl.p"] == {"calls": 3, "fires": 1}


def test_chaos_preempt_kind(monkeypatch):
    """preempt delivers the eviction notice to the process itself — with
    a handler installed the flag is set, nothing dies."""
    sent = []
    monkeypatch.setattr(chaos, "_preempt_action",
                        lambda msg: sent.append(msg))
    chaos.arm("pr.p", "preempt", first=1)
    chaos.point("pr.p")
    assert len(sent) == 1 and "preempt" in sent[0]


def test_chaos_preempt_reaches_installed_handler():
    """Unpatched path: the chaos preempt kind raises a real SIGTERM which
    an installed PreemptionHandler absorbs into its flag."""
    with PreemptionHandler(grace_ms=60000) as ph:
        chaos.arm("pr.live", "preempt", first=1)
        chaos.point("pr.live")
        # signal delivery to the main thread is immediate on return from
        # the C call, but don't rely on exact timing
        for _ in range(100):
            if ph.triggered():
                break
            time.sleep(0.01)
        assert ph.triggered()
        assert ph.signum == signal.SIGTERM


def test_chaos_spec_rejects_bad_kind():
    with pytest.raises(ValueError):
        chaos.arm_from_env("p.x:evicted")


# ---------------------------------------------------------------------------
# PreemptionHandler
# ---------------------------------------------------------------------------

def test_preemption_handler_grace_window_fake_clock():
    clk = [50.0]
    ph = PreemptionHandler(grace_ms=1000, clock=lambda: clk[0])
    assert not ph.triggered()
    assert ph.deadline_left_ms() is None
    ph.trigger(signal.SIGUSR1)
    assert ph.triggered() and ph.signum == signal.SIGUSR1
    assert ph.deadline_left_ms() == pytest.approx(1000.0)
    clk[0] += 0.6
    assert ph.deadline_left_ms() == pytest.approx(400.0)
    # repeated notices do NOT extend the grace window
    ph.trigger(signal.SIGTERM)
    assert ph.signum == signal.SIGUSR1
    assert ph.deadline_left_ms() == pytest.approx(400.0)
    ph.reset()
    assert not ph.triggered() and ph.deadline_left_ms() is None


def test_preemption_handler_real_signal_and_uninstall():
    before = signal.getsignal(signal.SIGUSR1)
    ph = PreemptionHandler(grace_ms=60000,
                           signals=(signal.SIGUSR1,)).install()
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        for _ in range(100):
            if ph.triggered():
                break
            time.sleep(0.01)
        assert ph.triggered() and ph.signum == signal.SIGUSR1
    finally:
        ph.uninstall()
    assert signal.getsignal(signal.SIGUSR1) == before


# ---------------------------------------------------------------------------
# collective watchdog
# ---------------------------------------------------------------------------

def test_collective_watchdog_pass_and_error_relay():
    wd = CollectiveWatchdog(deadline_ms=5000)
    assert wd.run(lambda a, b: a + b, 2, 3) == 5
    with pytest.raises(ValueError, match="boom"):
        wd.run(lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert wd.guarded == 2 and wd.timeouts == 0


def test_collective_watchdog_aborts_hung_collective():
    """The acceptance wedge: an operation that blocks forever (peer died
    mid-allreduce) is aborted at the deadline instead of hanging."""
    release = threading.Event()
    aborted = []
    wd = CollectiveWatchdog(deadline_ms=80,
                            on_abort=lambda op, d: aborted.append(op))
    t0 = time.monotonic()
    with pytest.raises(CollectiveTimeout):
        wd.run(release.wait, op="test.allreduce")
    assert time.monotonic() - t0 < 5.0  # aborted, not wedged
    assert aborted == ["test.allreduce"]
    assert wd.timeouts == 1
    release.set()  # unpark the abandoned helper thread


def test_collective_watchdog_disabled_is_inline():
    wd = CollectiveWatchdog(deadline_ms=0)
    tid = threading.get_ident()
    assert wd.run(threading.get_ident) == tid  # no helper thread at all


def test_guard_collective_env_knob(monkeypatch):
    from mxnet_tpu.resilience.elastic import guard_collective

    release = threading.Event()
    # knob off: runs inline
    monkeypatch.delenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS",
                       raising=False)
    assert guard_collective(lambda: 7) == 7
    # knob on: the hung call is aborted
    monkeypatch.setenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS", "60")
    with pytest.raises(CollectiveTimeout):
        guard_collective(release.wait, op="knob.test")
    release.set()


def test_kvstore_allreduce_guarded(monkeypatch):
    """The kvstore wiring: a hung cross-process allreduce surfaces as
    CollectiveTimeout out of push() (not retried — the peer is gone)."""
    from mxnet_tpu import kvstore as kv_mod

    release = threading.Event()
    monkeypatch.setattr(kv_mod, "_cross_process_allreduce",
                        lambda x: release.wait() or x)
    monkeypatch.setattr(kv_mod.jax, "process_count", lambda: 2)
    monkeypatch.setenv("MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS", "80")
    kv = mx.kv.create("dist_sync")
    kv.init("w", nd.ones((2,)))
    t0 = time.monotonic()
    with pytest.raises(CollectiveTimeout):
        kv.push("w", nd.ones((2,)))
    assert time.monotonic() - t0 < 10.0
    release.set()


# ---------------------------------------------------------------------------
# DeviceFeed.flush + step_stream preemption
# ---------------------------------------------------------------------------

def _small_trainer(dp=2):
    import jax

    mx.random.seed(0)
    np.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, 8)))
    mesh = parallel.make_mesh(dp=dp, devices=jax.devices()[:dp])
    return parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05}, mesh=mesh)


def _feed_batches(n, seed=13):
    rng = np.random.RandomState(seed)
    return [(mx.nd.array(rng.rand(8, 8).astype("float32")),
             mx.nd.array(rng.randint(0, 4, (8,)).astype("float32")))
            for _ in range(n)]


def test_devicefeed_flush_releases_staged_batches():
    from mxnet_tpu.parallel.datafeed import DeviceFeed

    t = _small_trainer()
    batches = _feed_batches(6)
    feed = DeviceFeed(batches, mesh=t.mesh, depth=3, name="flush_test")
    try:
        feed.prefill()
        n = feed.flush()
        assert n >= 1
        assert feed.stats()["flushed"] == n
        # the feed stays usable: the next iteration restages from the
        # source top (the replay-after-restart contract)
        first = next(iter(feed))
        np.testing.assert_array_equal(
            np.asarray(first[1]), batches[0][1].asnumpy())
    finally:
        feed.close()


def test_step_stream_preemption_at_chunk_boundary():
    """An eviction notice stops step_stream BETWEEN chunks: completed
    chunks are committed to _t, the raise happens before the next chunk
    consumes from the feed, and flush() releases the staged remainder."""
    from mxnet_tpu.parallel.datafeed import DeviceFeed

    class TriggerOnSecondCheck:
        def __init__(self):
            self.checks = 0

        def triggered(self):
            self.checks += 1
            return self.checks > 1

    t = _small_trainer()
    feed = DeviceFeed(_feed_batches(8), mesh=t.mesh, depth=4,
                      name="preempt_test")
    try:
        with pytest.raises(Preempted) as ei:
            t.step_stream(feed, steps=8, chunk=2,
                          preemption=TriggerOnSecondCheck())
        # exactly one chunk (2 steps) committed before the notice
        assert t._t == 2 and ei.value.step == 2
        feed.flush()  # the staged-ahead batches release cleanly
    finally:
        feed.close()


# ---------------------------------------------------------------------------
# /healthz + /metrics membership surface
# ---------------------------------------------------------------------------

def test_elastic_health_degrades_on_pending_preemption(tmp_path):
    from mxnet_tpu.resilience import elastic

    with PreemptionHandler(grace_ms=60000) as ph:
        assert elastic.health()["status"] == "ok"
        ph.trigger()
        h = elastic.health()
        assert h == {"status": "degraded", "reason": "preemption_pending"}
        g = elastic.membership_gauge()
        assert g["preemption_pending"] is True
    assert elastic.health()["status"] == "ok"


def test_elastic_health_degrades_on_lost_member(tmp_path):
    from mxnet_tpu.resilience.elastic import (ElasticCoordinator,
                                              ElasticMember)
    from mxnet_tpu.resilience import elastic

    clk = [10.0]
    d = str(tmp_path / "rdzv")
    m = ElasticMember(d, 0, world_size=1, clock=lambda: clk[0])
    m.register()
    coord = ElasticCoordinator(d, world_size=1, deadline_ms=1000,
                               clock=lambda: clk[0])
    assert elastic.health()["status"] == "ok"
    clk[0] += 5.0
    h = elastic.health()
    assert h["status"] == "degraded" and h["reason"] == "members_lost"
    assert h["dead"] == [0]
    g = elastic.membership_gauge()
    assert g["membership"]["alive"] == 0 and g["membership"]["dead"] == [0]
    m.leave("done")
    clk[0] += 1.0  # past the gauge snapshot's TTL (same injected clock)
    assert elastic.health()["status"] == "ok"
    del coord  # drop the gauge registration for later tests


# ---------------------------------------------------------------------------
# launcher hardening (plain mode) + supervise over the ssh shim
# ---------------------------------------------------------------------------

_RANK_SCRIPT = (
    "import os, sys, time\n"
    "rank = int(os.environ['MXTPU_PROCESS_ID'])\n"
    "if rank == 0:\n"
    "    time.sleep(0.3)\n"
    "    sys.exit(3)\n"
    "time.sleep(300)\n")


def test_launch_plain_kills_group_on_first_failure(tmp_path):
    """Rank 0 dies rc=3; the launcher must kill rank 1 (a 300s sleeper)
    instead of waiting it out, and exit with the first failing code."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--launcher", "local",
         sys.executable, "-c", _RANK_SCRIPT],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 3, proc.stderr[-2000:]
    assert time.monotonic() - t0 < 60.0  # the sleeper was killed
    assert '"0": 3' in proc.stderr  # per-worker exit codes reported


def _ssh_shim(tmp_path):
    shim = tmp_path / "fake_ssh"
    shim.write_text(
        "#!/bin/sh\n"
        "while true; do\n"
        "  case \"$1\" in\n"
        "    -o) shift 2;;\n"
        "    -n|-q|-T) shift;;\n"
        "    *) break;;\n"
        "  esac\n"
        "done\n"
        "host=\"$1\"; shift\n"
        "exec /bin/sh -c \"$@\"\n")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    return shim


def test_supervise_honors_ssh_shim(tmp_path):
    """The supervise path spawns through the same MXTPU_SSH seam as the
    plain ssh launcher (CI has no sshd)."""
    hostfile = tmp_path / "hosts"
    hostfile.write_text("hostA\nhostB\n")
    events = tmp_path / "events.jsonl"
    env = dict(os.environ)
    env["MXTPU_SSH"] = str(_ssh_shim(tmp_path))
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--supervise",
         "--launcher", "ssh", "-H", str(hostfile),
         "--event-log", str(events),
         sys.executable, "-c",
         "import os; assert os.environ['MXTPU_RDZV_DIR']"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    kinds = [json.loads(l)["event"] for l in events.read_text().splitlines()]
    assert kinds[0] == "generation_start" and "run_complete" in kinds


# ---------------------------------------------------------------------------
# supervised end-to-end: host loss + real SIGTERM (ISSUE-6 acceptance)
# ---------------------------------------------------------------------------

def _worker_env(workdir, **extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the supervisor re-spreads the devices
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
                "ELASTIC_WORKDIR": str(workdir)})
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _events(path):
    return [json.loads(l) for l in open(path).read().splitlines()]


def _reference_replay(tmp_path, snapshot, devices, steps):
    """Restore-and-replay from `snapshot` at the surviving topology — the
    bitwise baseline the resumed supervised run must match."""
    ref = tmp_path / "ref"
    os.makedirs(ref / "ckpt-rank0")
    shutil.copytree(snapshot, ref / "ckpt-rank0" / "resume_ckpt")
    env = _worker_env(ref, ELASTIC_STEPS=steps, MXTPU_GENERATION=1)
    env["XLA_FLAGS"] = \
        "--xla_force_host_platform_device_count=%d" % devices
    proc = subprocess.run([sys.executable, WORKER], env=env,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-3000:]
    with open(ref / "out" / "result_gen1_rank0.json") as f:
        return json.load(f)


def test_supervised_host_loss_reshard_bitwise(tmp_path):
    """Worker 1 dies abruptly (injected host_loss, exit 137) at step 5 of
    10. The supervisor evicts it (restart budget 0), re-forms at world
    size 1 with the full 4-device pool (reshard 2 -> 4), and the resumed
    trajectory is bitwise-equal to restore-and-replay from the restored
    snapshot."""
    steps = 12
    events = tmp_path / "events.jsonl"
    # slow steps (150 ms latency injection) so the survivor is still
    # mid-run when the supervisor reacts to the loss — the teardown
    # SIGTERM then exercises the emergency-checkpoint path for real
    env = _worker_env(tmp_path, ELASTIC_STEPS=steps, ELASTIC_CKPT_EVERY=2,
                      ELASTIC_FAIL_RANK=1, ELASTIC_FAIL_STEP=5,
                      ELASTIC_FAIL_KIND="host_loss",
                      ELASTIC_STEP_SLOW_MS=150)
    proc = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--supervise",
         "--max-restarts", "0", "--total-devices", "4",
         "--rdzv-dir", str(tmp_path / "rdzv"),
         "--event-log", str(events), "--grace-ms", "20000",
         sys.executable, WORKER],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, \
        "supervised run failed:\n%s" % proc.stderr[-4000:]

    evs = _events(events)
    fail = next(e for e in evs if e["event"] == "worker_failed")
    assert fail["rank"] == 1 and fail["rc"] == 137
    evict = next(e for e in evs if e["event"] == "evicted")
    assert evict["world"] == 1
    assert any(e["event"] == "run_complete" and e["world"] == 1
               for e in evs)

    with open(tmp_path / "out" / "result_gen1_rank0.json") as f:
        resumed = json.load(f)
    # the re-formed world absorbed the whole device pool: a real reshard
    assert resumed["devices"] == 4 and resumed["world"] == 1
    assert 0 < resumed["start_step"] < steps
    assert resumed["end_step"] == steps

    ref = _reference_replay(tmp_path,
                            tmp_path / "out" / "restored_gen1_rank0",
                            devices=4, steps=steps)
    assert ref["start_step"] == resumed["start_step"]
    assert ref["losses"] == resumed["losses"]          # bitwise
    assert ref["params_sha256"] == resumed["params_sha256"]


def test_supervised_real_sigterm_emergency_checkpoint(tmp_path):
    """A REAL external SIGTERM to worker 1: its PreemptionHandler writes
    the emergency checkpoint inside the grace window and exits 75
    (EXIT_PREEMPTED); the supervisor evicts, re-forms at world 1, and the
    run completes all steps."""
    steps = 30
    events = tmp_path / "events.jsonl"
    rdzv = tmp_path / "rdzv"
    env = _worker_env(tmp_path, ELASTIC_STEPS=steps, ELASTIC_CKPT_EVERY=2,
                      ELASTIC_STEP_SLOW_MS=200)
    proc = subprocess.Popen(
        [sys.executable, LAUNCH, "-n", "2", "--supervise",
         "--max-restarts", "0", "--total-devices", "4",
         "--rdzv-dir", str(rdzv), "--event-log", str(events),
         "--grace-ms", "20000",
         sys.executable, WORKER],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        # wait until rank 1 registered and made step progress, then
        # deliver the eviction notice the cloud would
        member = rdzv / "member-00001.json"
        target = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if member.exists():
                try:
                    rec = json.loads(member.read_text())
                except ValueError:
                    rec = {}
                if rec.get("status") == "up" and rec.get("step", 0) >= 2:
                    target = rec["pid"]
                    break
            time.sleep(0.1)
        assert target is not None, "rank 1 never made progress"
        os.kill(target, signal.SIGTERM)
        out, err = proc.communicate(timeout=360)
    except BaseException:
        proc.kill()
        raise
    assert proc.returncode == 0, "supervised run failed:\n%s" % err[-4000:]

    evs = _events(events)
    fail = next(e for e in evs if e["event"] == "worker_failed")
    assert fail["reason"] == "preempted" and fail["rc"] == EXIT_PREEMPTED
    assert any(e["event"] == "evicted" and e["world"] == 1 for e in evs)
    assert any(e["event"] == "run_complete" for e in evs)
    with open(tmp_path / "out" / "result_gen1_rank0.json") as f:
        resumed = json.load(f)
    assert resumed["end_step"] == steps
    assert resumed["start_step"] >= 1  # resumed from a real checkpoint
