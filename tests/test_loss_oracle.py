"""Gluon loss zoo vs closed-form numpy oracles — semantics from reference
`python/mxnet/gluon/loss.py` and `tests/python/unittest/test_loss.py`."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon

L = gluon.loss
rng = np.random.RandomState(0)


def _nd(a):
    return mx.nd.array(np.asarray(a, "float32"))


def test_l1_l2():
    p = rng.randn(4, 3).astype("float32")
    t = rng.randn(4, 3).astype("float32")
    np.testing.assert_allclose(L.L1Loss()(_nd(p), _nd(t)).asnumpy(),
                               np.abs(p - t).mean(axis=1), rtol=1e-5)
    np.testing.assert_allclose(L.L2Loss()(_nd(p), _nd(t)).asnumpy(),
                               ((p - t) ** 2).mean(axis=1) / 2, rtol=1e-5)


def test_huber():
    p = np.array([[0.0, 3.0]], "float32")
    t = np.array([[0.5, 0.0]], "float32")
    out = L.HuberLoss(rho=1.0)(_nd(p), _nd(t)).asnumpy()
    # |0-0.5|=0.5 -> quadratic 0.125 ; |3|=3 -> linear 3-0.5=2.5
    np.testing.assert_allclose(out, [(0.125 + 2.5) / 2], rtol=1e-5)


def test_sigmoid_bce_from_logits_and_probs():
    z = rng.randn(3, 4).astype("float32")
    y = (rng.rand(3, 4) > 0.5).astype("float32")
    ref = (np.maximum(z, 0) - z * y + np.log1p(np.exp(-np.abs(z))))
    out = L.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)(
        _nd(z), _nd(y)).asnumpy()
    np.testing.assert_allclose(out, ref.mean(axis=1), rtol=1e-4)
    p = 1 / (1 + np.exp(-z))
    out2 = L.SigmoidBinaryCrossEntropyLoss(from_sigmoid=True)(
        _nd(p), _nd(y)).asnumpy()
    np.testing.assert_allclose(out2, out, rtol=1e-3, atol=1e-4)


def test_softmax_ce_sparse_and_dense():
    z = rng.randn(5, 4).astype("float32")
    y = rng.randint(0, 4, 5).astype("float32")
    ls = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
    ref = -ls[np.arange(5), y.astype(int)]
    out = L.SoftmaxCrossEntropyLoss()(_nd(z), _nd(y)).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4)
    onehot = np.eye(4, dtype="float32")[y.astype(int)]
    out2 = L.SoftmaxCrossEntropyLoss(sparse_label=False)(
        _nd(z), _nd(onehot)).asnumpy()
    np.testing.assert_allclose(out2, ref, rtol=1e-4)


def test_kldiv():
    p = rng.rand(3, 4).astype("float32") + 0.1
    p /= p.sum(axis=1, keepdims=True)
    q = rng.rand(3, 4).astype("float32") + 0.1
    q /= q.sum(axis=1, keepdims=True)
    logq = np.log(q)
    out = L.KLDivLoss(from_logits=True)(_nd(logq), _nd(p)).asnumpy()
    ref = (p * (np.log(p) - logq)).mean(axis=1)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)


def test_hinge_and_squared_hinge():
    z = np.array([[2.0, -0.5]], "float32")
    y = np.array([[1.0, -1.0]], "float32")  # margins: 1-2=-1->0 ; 1-0.5=0.5
    out = L.HingeLoss()(_nd(z), _nd(y)).asnumpy()
    np.testing.assert_allclose(out, [0.25], rtol=1e-5)
    out2 = L.SquaredHingeLoss()(_nd(z), _nd(y)).asnumpy()
    np.testing.assert_allclose(out2, [0.125], rtol=1e-5)


def test_logistic():
    z = np.array([[0.0, 2.0]], "float32")
    y = np.array([[1.0, -1.0]], "float32")
    ref = np.log1p(np.exp(-z * y)).mean()
    out = L.LogisticLoss()(_nd(z), _nd(y)).asnumpy()
    np.testing.assert_allclose(out, [ref], rtol=1e-5)


def test_poisson_nll():
    pred = np.array([[1.0, 2.0]], "float32")
    t = np.array([[0.0, 3.0]], "float32")
    ref = (pred - t * np.log(pred + 1e-8)).mean()
    out = L.PoissonNLLLoss(from_logits=False)(_nd(pred), _nd(t)).asnumpy()
    np.testing.assert_allclose(out, [ref], rtol=1e-4)


def test_cosine_embedding():
    a = rng.randn(2, 5).astype("float32")
    b = rng.randn(2, 5).astype("float32")
    y = np.array([1.0, -1.0], "float32")
    cos = (a * b).sum(1) / (np.linalg.norm(a, axis=1) *
                            np.linalg.norm(b, axis=1))
    ref = np.where(y == 1, 1 - cos, np.maximum(0, cos))
    out = L.CosineEmbeddingLoss()(_nd(a), _nd(b), _nd(y)).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_triplet():
    a = rng.randn(3, 4).astype("float32")
    p = rng.randn(3, 4).astype("float32")
    n = rng.randn(3, 4).astype("float32")
    ref = np.maximum(((a - p) ** 2).sum(1) - ((a - n) ** 2).sum(1) + 1.0,
                     0.0)
    out = L.TripletLoss(margin=1.0)(_nd(a), _nd(p), _nd(n)).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_weight_and_sample_weight():
    p = np.ones((2, 3), "float32")
    t = np.zeros((2, 3), "float32")
    out = L.L1Loss(weight=2.0)(_nd(p), _nd(t)).asnumpy()
    np.testing.assert_allclose(out, [2.0, 2.0])
    sw = np.array([[1.0], [0.0]], "float32")
    out2 = L.L1Loss()(_nd(p), _nd(t), _nd(sw)).asnumpy()
    np.testing.assert_allclose(out2, [1.0, 0.0])


def test_softmax_ce_ignores_negative_labels():
    """label -1 (the native RecordIO corrupt-record marker) contributes
    ZERO loss in both the gluon loss and the softmax_cross_entropy op
    (round-3 advisor finding: -1 resolved as the last class)."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    rng = np.random.RandomState(3)
    pred = rng.randn(6, 4).astype("float32")
    lab = np.array([0, 1, -1, 2, -1, 3], "float32")
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    out = L(mx.nd.array(pred), mx.nd.array(lab)).asnumpy()
    logp = pred - np.log(np.exp(pred - pred.max(-1, keepdims=True))
                         .sum(-1, keepdims=True)) - pred.max(-1,
                                                             keepdims=True)
    expect = np.array([-logp[i, int(l)] if l >= 0 else 0.0
                       for i, l in enumerate(lab)], "float32")
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)

    op_out = mx.nd.softmax_cross_entropy(
        mx.nd.array(pred), mx.nd.array(lab)).asnumpy()
    np.testing.assert_allclose(op_out, expect.sum(), rtol=1e-5, atol=1e-5)
