"""Model-parallel MLP: layers placed on different devices via group2ctx.

Role parity: reference `example/model-parallel/` (the LSTM/matrix-fact
examples split a model's LAYERS across GPUs with `group2ctx`; activations
cross devices at group boundaries while each device holds only its own
parameters).

TPU-native notes: on a TPU pod the same placement maps stages onto mesh
slices and XLA inserts the ICI transfers; here the runnable demo uses the
virtual CPU mesh (`XLA_FLAGS=--xla_force_host_platform_device_count=8`)
exactly like the test-suite does, so the placement machinery — symbol
`ctx_group` attrs, executor `group2ctx` device resolution, cross-device
forward AND backward — is fully exercised on any host. For production
pipeline-parallel training see `mxnet_tpu.parallel` (GPipe ppermute ring),
which subsumes this per-layer style at scale.

Usage:  XLA_FLAGS=--xla_force_host_platform_device_count=2 \
        JAX_PLATFORMS=cpu python mlp_model_parallel.py [--steps 200]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def build_split_mlp(hidden=64, classes=10):
    """Stage 1 (dev1): input -> hidden; stage 2 (dev2): hidden -> logits.
    The `ctx_group` attr on each scope is the reference's placement
    annotation (symbol.py AttrScope(ctx_group=...))."""
    with sym.AttrScope(ctx_group="dev1"):
        data = sym.var("data")
        h = sym.Activation(
            sym.FullyConnected(data, num_hidden=hidden, name="fc1"),
            act_type="relu", name="act1")
    with sym.AttrScope(ctx_group="dev2"):
        label = sym.var("softmax_label")
        logits = sym.FullyConnected(h, num_hidden=classes, name="fc2")
        net = sym.SoftmaxOutput(logits, label, name="softmax")
    return net


def train(steps=200, batch=32, in_dim=20, classes=10, lr=0.1, log=print):
    import jax
    devs = jax.devices()
    if len(devs) < 2:
        raise SystemExit(
            "need >=2 devices: run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=2")
    group2ctx = {"dev1": mx.cpu(0) if devs[0].platform == "cpu"
                 else mx.tpu(0),
                 "dev2": mx.cpu(1) if devs[1].platform == "cpu"
                 else mx.tpu(1)}

    rng = np.random.RandomState(0)
    w_true = rng.randn(in_dim, classes).astype("float32")
    n_data = 16 * batch  # fixed dataset, cycled over epochs
    x_all = rng.randn(n_data, in_dim).astype("float32")
    y_all = (x_all @ w_true).argmax(axis=1).astype("float32")

    net = build_split_mlp(classes=classes)
    # bind with explicit group2ctx placement
    arg_shapes, _, _ = net.infer_shape(data=(batch, in_dim),
                                       softmax_label=(batch,))
    args = {n: nd.array(rng.uniform(-0.1, 0.1, s).astype("float32"))
            for n, s in zip(net.list_arguments(), arg_shapes)}
    grads = {n: nd.zeros(s)
             for n, s in zip(net.list_arguments(), arg_shapes)}
    ex = net.bind(mx.cpu(0), args, args_grad=grads, group2ctx=group2ctx)

    # both stages really resolved to distinct devices
    placed = set(ex._placement.values())
    assert len(placed) == 2, "expected 2 distinct devices, got %r" % placed

    first = last = None
    for step in range(steps):
        s = (step * batch) % n_data
        args["data"][:] = x_all[s:s + batch]
        args["softmax_label"][:] = y_all[s:s + batch]
        out = ex.forward(is_train=True)[0]
        ex.backward()
        p = out.asnumpy()
        loss = -np.log(np.maximum(
            p[np.arange(batch), y_all[s:s + batch].astype(int)], 1e-8)
        ).mean()
        if first is None:
            first = loss
        last = loss
        for name in ("fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"):
            args[name] -= lr * grads[name]
        if step % 50 == 0:
            log("step %d loss %.4f" % (step, loss))
    log("loss %.4f -> %.4f (stages on %d devices)"
        % (first, last, len(placed)))
    return first, last, len(placed)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    args = ap.parse_args()
    train(steps=args.steps)
