"""BERT pretraining example (BASELINE.md reference config "BERT-base
pretraining"; role of the reference ecosystem's GluonNLP pretraining
script, on this framework's mesh-first substrate).

Synthetic-corpus masked-LM + next-sentence pretraining:

    python example/bert/pretrain_bert.py                 # bert-tiny, CPU ok
    python example/bert/pretrain_bert.py --model base    # BERT-base
    python example/bert/pretrain_bert.py --dp 4 --tp 2   # mesh sharding

The training step is ONE fused SPMD program (ShardedTrainer): forward,
backward, gradient allreduce over the dp axis, Adam update — with
Megatron tensor-parallel sharding of qkv/proj/ffn weights over tp.
"""
import argparse
import sys
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel
from mxnet_tpu.gluon.block import HybridBlock
from mxnet_tpu.models.bert import (bert_tiny, bert_base,
                                   BERTPretrainingLoss)
from mxnet_tpu.models.transformer import tp_rules


def synthetic_batch(rng, batch, seq_len, vocab, n_masks):
    tokens = rng.integers(4, vocab, (batch, seq_len)).astype("float32")
    segments = np.zeros((batch, seq_len), "float32")
    half = seq_len // 2
    segments[:, half:] = 1.0
    positions = np.stack([rng.choice(seq_len, n_masks, replace=False)
                          for _ in range(batch)]).astype("float32")
    labels = np.take_along_axis(tokens, positions.astype(int), axis=1)
    masked = tokens.copy()
    np.put_along_axis(masked, positions.astype(int), 3.0, axis=1)  # [MASK]=3
    weights = np.ones((batch, n_masks), "float32")
    nsp = rng.integers(0, 2, (batch,)).astype("float32")
    return masked, segments, positions, labels, weights, nsp


class PretrainStep(HybridBlock):
    """Computes the full pretraining loss inside the block, so the trainer
    sees a scalar output: data = (tokens, segments, positions, labels,
    weights, nsp_labels), label = unused dummy."""

    def __init__(self, bert, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.bert = bert
        self.loss = BERTPretrainingLoss(picked=True)

    def hybrid_forward(self, F, tokens, segments, positions, labels,
                       weights, nsp_labels):
        # gather-first decode: the MLM head runs on the M masked slots only
        # (reference GluonNLP decode path; 6.4x less vocab-head work at
        # s128/M20 than full-sequence logits)
        _, _, mlm_logits, nsp_logits = self.bert(tokens, segments, None,
                                                 positions)
        return self.loss(mlm_logits, nsp_logits, labels, positions,
                         weights, nsp_labels)


class PretrainLoss:
    """Identity: the block already produced the scalar loss."""

    def __call__(self, out, _dummy):
        return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny", choices=["tiny", "base"])
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--n-masks", type=int, default=20)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--dp", type=int, default=-1)
    ap.add_argument("--tp", type=int, default=1)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.default_rng(0)
    if args.model == "base":
        net = bert_base(vocab_size=args.vocab, max_length=args.seq_len)
    else:
        net = bert_tiny(vocab_size=args.vocab, max_length=args.seq_len)
    net.initialize(mx.init.Xavier())

    step = PretrainStep(net)
    mesh = parallel.make_mesh(dp=args.dp, tp=args.tp)
    trainer = parallel.ShardedTrainer(
        step, PretrainLoss(), "adam", {"learning_rate": args.lr},
        mesh=mesh, param_rules=tp_rules() if args.tp > 1 else None)

    print("mesh:", dict(mesh.shape), file=sys.stderr)
    t0 = time.time()
    for i in range(args.steps):
        m, s, p, l, w, nsp = synthetic_batch(
            rng, args.batch_size, args.seq_len, args.vocab, args.n_masks)
        loss = trainer.step(
            (nd.array(m), nd.array(s), nd.array(p), nd.array(l),
             nd.array(w), nd.array(nsp)),
            nd.zeros((args.batch_size,)))
        if i % 5 == 0 or i == args.steps - 1:
            print("step %3d  loss %.4f" % (i, float(loss.asnumpy())))
    dt = time.time() - t0
    print("done: %d steps in %.1fs (%.1f seq/s)"
          % (args.steps, dt, args.steps * args.batch_size / dt))


if __name__ == "__main__":
    main()
