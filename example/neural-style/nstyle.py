"""Neural style transfer (reference `example/neural-style/nstyle.py` —
optimize the INPUT image against content + Gram-matrix style losses from
a pretrained VGG; `model_vgg19.py` loads fixed weights).

Port on a compact fixed-weight CNN (pretrained-VGG stand-in, weights
loaded from a deterministic file to exercise the load path): the
variable being optimized is the image itself — `x.attach_grad()` +
`autograd.record` + manual Adam on the pixel tensor, exactly the
reference's training loop structure (nstyle.py:159 train loop).

    python example/neural-style/nstyle.py [--steps 60]
"""
import argparse
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, nd
from mxnet_tpu.gluon import nn

SIZE = 32


def build_extractor(seed=0):
    """3-stage conv feature extractor with FIXED (non-trainable) weights,
    saved+loaded through the params file format like the reference loads
    vgg19.params."""
    net = nn.HybridSequential()
    net.add(nn.Conv2D(16, 3, padding=1, activation="relu", in_channels=3),
            nn.Conv2D(32, 3, strides=2, padding=1, activation="relu",
                      in_channels=16),
            nn.Conv2D(64, 3, strides=2, padding=1, activation="relu",
                      in_channels=32))
    mx.random.seed(seed)
    net.initialize(mx.init.Xavier())
    path = os.path.join(tempfile.gettempdir(), "nstyle_extractor.params")
    net.save_parameters(path)
    net2 = nn.HybridSequential()
    net2.add(nn.Conv2D(16, 3, padding=1, activation="relu", in_channels=3),
             nn.Conv2D(32, 3, strides=2, padding=1, activation="relu",
                       in_channels=16),
             nn.Conv2D(64, 3, strides=2, padding=1, activation="relu",
                       in_channels=32))
    net2.load_parameters(path)   # the pretrained-weight load path
    for p in net2.collect_params().values():
        p.grad_req = "null"      # frozen backbone
    return net2


def features(net, x):
    """Per-stage activations (the reference taps relu1_1/relu2_1/...)."""
    feats = []
    h = x
    for layer in net:
        h = layer(h)
        feats.append(h)
    return feats


def gram(f):
    B, C, H, W = f.shape
    m = f.reshape((C, H * W))
    return nd.dot(m, m.T) / (C * H * W)


def make_images(seed=0):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE] / SIZE
    content = np.stack([yy, xx, (xx + yy) / 2]).astype(np.float32)[None]
    stripes = np.sin(16 * np.pi * xx)[None].repeat(3, 0).astype(np.float32)
    style = stripes[None] + 0.05 * rng.standard_normal(
        (1, 3, SIZE, SIZE)).astype(np.float32)
    return content, style


def train(steps=60, content_weight=1.0, style_weight=50.0, lr=0.05,
          log=print):
    net = build_extractor()
    content_np, style_np = make_images()
    content_feats = [f.asnumpy() for f in features(net, nd.array(content_np))]
    style_grams = [gram(f).asnumpy()
                   for f in features(net, nd.array(style_np))]

    x = nd.array(content_np.copy())
    x.attach_grad()
    losses = []
    m = v = None
    for it in range(steps):
        with ag.record():
            feats = features(net, x)
            c_loss = ((feats[-1] - nd.array(content_feats[-1])) ** 2).mean()
            s_loss = sum(((gram(f) - nd.array(g)) ** 2).sum()
                         for f, g in zip(feats, style_grams))
            loss = content_weight * c_loss + style_weight * s_loss
        loss.backward()
        g = x.grad.asnumpy()
        # Adam on the image (reference uses mx.optimizer on the pixel blob)
        m = g if m is None else 0.9 * m + 0.1 * g
        v = g * g if v is None else 0.999 * v + 0.001 * g * g
        x = nd.array(x.asnumpy() - lr * m / (np.sqrt(v) + 1e-8))
        x.attach_grad()
        losses.append(float(loss.asnumpy()))
        if it % 20 == 0:
            log("step %3d  loss %.4f (content %.4f style %.4f)"
                % (it, losses[-1], float(c_loss.asnumpy()),
                   float(s_loss.asnumpy())))
    final_grams = [gram(f).asnumpy() for f in features(net, x)]
    style_dist = sum(float(((a - b) ** 2).sum())
                     for a, b in zip(final_grams, style_grams))
    init_dist = sum(float(((gram(nd.array(f)).asnumpy() - g) ** 2).sum())
                    for f, g in zip(content_feats, style_grams))
    return losses, style_dist, init_dist


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    train(steps=ap.parse_args().steps)
