"""Denoising autoencoder trained end-to-end.

Role parity: reference `example/autoencoder/` (the stacked denoising
autoencoder demo: corrupt input, reconstruct, reconstruction MSE as the
metric). The reference's greedy layerwise PRETRAINING phase is omitted:
end-to-end training with modern initializers reaches the manifold
directly — the corrupt->encode->decode->MSE capability is the parity
surface here.

Usage:  python train_autoencoder.py [--epochs 8]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def make_data(n=768, dim=64, rank=6, seed=0):
    """Low-rank structured data: the AE must discover the 6-d manifold."""
    rng = np.random.RandomState(seed)
    basis = rng.randn(rank, dim).astype("float32")
    codes = rng.randn(n, rank).astype("float32")
    x = np.tanh(codes @ basis)
    return x.astype("float32")


class DAE(gluon.Block):
    def __init__(self, dim, hidden, bottleneck, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.enc1 = gluon.nn.Dense(hidden, activation="relu")
            self.enc2 = gluon.nn.Dense(bottleneck)
            self.dec1 = gluon.nn.Dense(hidden, activation="relu")
            self.dec2 = gluon.nn.Dense(dim)

    def encode(self, x):
        return self.enc2(self.enc1(x))

    def forward(self, x):
        return self.dec2(self.dec1(self.encode(x)))


def train(epochs=8, noise=0.2, batch=64, log=print):
    x = make_data()
    net = DAE(x.shape[1], 32, 8)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(1)
    first = last = None
    for epoch in range(epochs):
        total, nb = 0.0, 0
        for s in range(0, len(x), batch):
            clean = x[s:s + batch]
            noisy = clean + rng.randn(*clean.shape).astype("float32") * noise
            xb, yb = nd.array(noisy), nd.array(clean)
            with ag.record():
                loss = loss_fn(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy())
            nb += 1
        mse = total / nb
        if first is None:
            first = mse
        last = mse
        log("epoch %d: denoise MSE %.5f" % (epoch, mse))
    # reconstruction quality on clean inputs
    rec = net(nd.array(x)).asnumpy()
    rec_mse = float(((rec - x) ** 2).mean())
    code = net.encode(nd.array(x[:4])).asnumpy()
    log("clean reconstruction MSE %.5f, code shape %s"
        % (rec_mse, code.shape))
    return first, last, rec_mse


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    args = ap.parse_args()
    train(epochs=args.epochs)
