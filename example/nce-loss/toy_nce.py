"""Noise-contrastive estimation for large-softmax training (reference
`example/nce-loss/nce.py` nce_loss + `toy_nce.py` — avoid the full
softmax by scoring the true class against k sampled noise classes with
per-class embedded weights).

Exercises sparse embedding gradients: each step touches only the rows of
the output-embedding matrix named by (label + sampled noise), and the
test asserts untouched rows keep their initial values — the gradient
really is row-sparse.

    python example/nce-loss/toy_nce.py [--epochs 10]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

VOCAB = 400
DIM = 32
K_NOISE = 8


class NCEModel(gluon.HybridBlock):
    """Input features -> hidden; per-class weight/bias via Embedding rows
    (reference nce.py:37 builds the same with embedded label weights)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.hidden = nn.Dense(DIM, activation="tanh", in_units=DIM)
            self.class_embed = nn.Embedding(VOCAB, DIM,
                                            prefix="class_embed_")
            self.class_bias = nn.Embedding(VOCAB, 1, prefix="class_bias_")

    def hybrid_forward(self, F, x, classes):
        # x: (B, DIM); classes: (B, 1+K) [true, noise...]
        h = self.hidden(x)                             # (B, D)
        w = self.class_embed(classes)                  # (B, 1+K, D)
        b = self.class_bias(classes).reshape(classes.shape)  # (B, 1+K)
        logits = (w * h.reshape((h.shape[0], 1, -1))).sum(axis=-1) + b
        return logits


def make_data(n, rng):
    """Each class has a characteristic direction; features = class dir +
    noise, so NCE must learn aligned class embeddings."""
    dirs = rng.standard_normal((VOCAB, DIM)).astype(np.float32)
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    # skew to a small head so many rows stay untouched
    labels = rng.integers(0, 40, n)
    X = dirs[labels] + 0.1 * rng.standard_normal((n, DIM)).astype(np.float32)
    return X.astype(np.float32), labels.astype(np.int64), dirs


def train(epochs=10, batch=32, lr=0.1, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = NCEModel()
    net.initialize(mx.init.Xavier())
    X, Y, dirs = make_data(512, rng)
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    trainer = gluon.Trainer(net.collect_params(), "adagrad",
                            {"learning_rate": lr})
    # snapshot BEFORE any update: the row-sparsity assertion compares
    # untouched rows against their true initial values
    net(nd.array(X[:1]), nd.array(np.zeros((1, 1 + K_NOISE), np.float32)))
    init_embed = net.class_embed.weight.data().asnumpy().copy()
    touched = set()
    losses = []
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            xb = X[i:i + batch]
            yb = Y[i:i + batch]
            # unigram-table noise: frequent-head classes only, like
            # the reference's frequency-weighted sampler -- tail
            # rows are never touched (asserted by the e2e test)
            noise = rng.integers(0, VOCAB // 2, (len(xb), K_NOISE))
            classes = np.concatenate([yb[:, None], noise], axis=1)
            touched.update(classes.reshape(-1).tolist())
            target = np.zeros((len(xb), 1 + K_NOISE), np.float32)
            target[:, 0] = 1.0
            with ag.record():
                logits = net(nd.array(xb),
                             nd.array(classes.astype(np.float32)))
                loss = loss_fn(logits, nd.array(target)).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        losses.append(tot / (len(X) // batch))
        if ep % 3 == 0:
            log("epoch %d  nce loss %.4f" % (ep, losses[-1]))
    final_embed = net.class_embed.weight.data().asnumpy()
    return losses, init_embed, final_embed, touched


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    train(epochs=ap.parse_args().epochs)
