"""CNN for sentence classification (reference
`example/cnn_text_classification/text_cnn.py` — Kim 2014: parallel conv
branches of widths 3/4/5 over the embedded sequence, max-over-time
pooling, concat, dropout, dense).

Synthetic sentiment data: sequences contain "positive"/"negative" token
n-grams whose ORDER matters within the window — exactly what the
multi-width convs detect and bag-of-words cannot.

    python example/cnn_text_classification/text_cnn.py [--epochs 8]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

VOCAB, SEQ, EMBED = 100, 20, 24
FILTERS = (3, 4, 5)
NUM_FILTER = 16
POS_TRIGRAM = [7, 8, 9]     # "very good movie"
NEG_TRIGRAM = [9, 8, 7]     # same bag, opposite order


class TextCNN(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, EMBED)
            self.convs = []
            for i, w in enumerate(FILTERS):
                c = nn.Conv1D(NUM_FILTER, w, in_channels=EMBED,
                              activation="relu", prefix="conv%d_" % w)
                self.convs.append(c)
                self.register_child(c)
            self.dropout = nn.Dropout(0.3)
            self.out = nn.Dense(2, in_units=NUM_FILTER * len(FILTERS))

    def hybrid_forward(self, F, tokens):
        e = self.embed(tokens)                   # (B, T, E)
        e = e.transpose((0, 2, 1))               # Conv1D wants NCW
        pooled = []
        for c in self.convs:
            h = c(e)                             # (B, F, T-w+1)
            pooled.append(F.max(h, axis=2))      # max over time
        h = F.concat(*pooled, dim=1)
        return self.out(self.dropout(h))


def make_data(n, rng):
    X = rng.integers(10, VOCAB, (n, SEQ))
    y = rng.integers(0, 2, n)
    pos = rng.integers(0, SEQ - 3, n)
    for i in range(n):
        tri = POS_TRIGRAM if y[i] == 1 else NEG_TRIGRAM
        X[i, pos[i]:pos[i] + 3] = tri
    return X.astype(np.float32), y.astype(np.float32)


def train(epochs=8, batch=32, lr=2e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = TextCNN()
    net.initialize(mx.init.Xavier())
    X, Y = make_data(512, rng)
    Xv, Yv = make_data(128, rng)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            with ag.record():
                out = net(nd.array(X[i:i + batch]))
                loss = loss_fn(out, nd.array(Y[i:i + batch])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        pred = net(nd.array(Xv)).asnumpy().argmax(1)
        acc = float((pred == Yv).mean())
        log("epoch %d  loss %.4f  val acc %.3f"
            % (ep, tot / (len(X) // batch), acc))
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    train(epochs=ap.parse_args().epochs)
