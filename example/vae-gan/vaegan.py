"""VAE-GAN (reference `example/vae-gan/vaegan_mxnet.py` — a VAE whose
decoder doubles as the GAN generator: encoder -> reparameterized latent
-> decoder, trained with KL + reconstruction + a discriminator
feature-matching adversarial term).

Port on synthetic two-mode image data; exercises the reparameterization
trick (differentiable sampling through random_normal), joint multi-net
training with separate Trainers, and detached-discriminator updates.

    python example/vae-gan/vaegan.py [--epochs 10]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

SIZE = 16
LATENT = 8


class Encoder(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential()
            self.body.add(nn.Conv2D(8, 3, strides=2, padding=1,
                                    activation="relu", in_channels=1),
                          nn.Conv2D(16, 3, strides=2, padding=1,
                                    activation="relu", in_channels=8),
                          nn.Flatten())
            self.mu = nn.Dense(LATENT, in_units=16 * 16)
            self.logvar = nn.Dense(LATENT, in_units=16 * 16)

    def hybrid_forward(self, F, x):
        h = self.body(x)
        return self.mu(h), self.logvar(h)


class Decoder(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.fc = nn.Dense(16 * 4 * 4, activation="relu",
                               in_units=LATENT)
            self.d1 = nn.Conv2DTranspose(8, 4, strides=2, padding=1,
                                         activation="relu", in_channels=16)
            self.d2 = nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                         in_channels=8)

    def hybrid_forward(self, F, z):
        h = self.fc(z).reshape((z.shape[0], 16, 4, 4))
        return F.sigmoid(self.d2(self.d1(h)))


class Discriminator(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.feat = nn.HybridSequential()
            self.feat.add(nn.Conv2D(8, 3, strides=2, padding=1,
                                    activation="relu", in_channels=1),
                          nn.Conv2D(16, 3, strides=2, padding=1,
                                    activation="relu", in_channels=8),
                          nn.Flatten(),
                          nn.Dense(32, activation="relu"))
            self.out = nn.Dense(1, in_units=32)

    def hybrid_forward(self, F, x):
        f = self.feat(x)
        return self.out(f), f


def make_data(n, rng):
    X = np.zeros((n, 1, SIZE, SIZE), np.float32)
    mode = rng.integers(0, 2, n)
    for i in range(n):
        if mode[i]:
            X[i, 0, 4:12, 4:12] = 1.0      # square mode
        else:
            yy, xx = np.ogrid[:SIZE, :SIZE]
            X[i, 0][(yy - 8) ** 2 + (xx - 8) ** 2 <= 16] = 1.0  # disk
    X += 0.05 * rng.standard_normal(X.shape).astype(np.float32)
    return np.clip(X, 0, 1), mode


def train(epochs=10, batch=32, lr=2e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    enc, dec, dis = Encoder(), Decoder(), Discriminator()
    for net in (enc, dec, dis):
        net.initialize(mx.init.Xavier())
    t_vae = gluon.Trainer(list(enc.collect_params().values()) +
                          list(dec.collect_params().values()),
                          "adam", {"learning_rate": lr})
    t_dis = gluon.Trainer(dis.collect_params(), "adam",
                          {"learning_rate": lr})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    X, _ = make_data(256, rng)
    hist = []
    for ep in range(epochs):
        tot_rec = tot_kl = tot_adv = 0.0
        for i in range(0, len(X), batch):
            xb = nd.array(X[i:i + batch])
            B = xb.shape[0]
            # --- discriminator step (VAE side detached) ----------------
            mu, logvar = enc(xb)
            z = mu + nd.exp(0.5 * logvar) * \
                nd.random.normal(0, 1, mu.shape)
            fake = dec(z)
            fake_d = fake.detach()
            with ag.record():
                real_logit, _ = dis(xb)
                fake_logit, _ = dis(fake_d)
                d_loss = bce(real_logit, nd.ones((B, 1))).mean() + \
                    bce(fake_logit, nd.zeros((B, 1))).mean()
            d_loss.backward()
            t_dis.step(1)
            # --- VAE step with adversarial feature matching ------------
            _, real_feat = dis(xb)
            real_feat = real_feat.detach()
            with ag.record():
                mu, logvar = enc(xb)
                z = mu + nd.exp(0.5 * logvar) * \
                    nd.random.normal(0, 1, mu.shape)
                rec = dec(z)
                rec_loss = ((rec - xb) ** 2).mean()
                kl = (-0.5 * (1 + logvar - mu ** 2 -
                              nd.exp(logvar))).mean()
                _, fake_feat = dis(rec)
                adv = ((fake_feat - real_feat) ** 2).mean()
                loss = rec_loss + 0.05 * kl + 0.1 * adv
            loss.backward()
            t_vae.step(1)
            tot_rec += float(rec_loss.asnumpy())
            tot_kl += float(kl.asnumpy())
            tot_adv += float(adv.asnumpy())
        nb = len(X) // batch
        hist.append((tot_rec / nb, tot_kl / nb, tot_adv / nb))
        log("epoch %d  rec %.4f  kl %.4f  adv-feat %.4f" % (ep, *hist[-1]))
    # sample from the prior through the decoder (the GAN-generator role)
    z = nd.random.normal(0, 1, (16, LATENT))
    samples = dec(z).asnumpy()
    return hist, samples


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    train(epochs=ap.parse_args().epochs)
