"""Speech recognition with CTC (reference `example/speech_recognition/` —
DeepSpeech-style: conv frontend over spectrograms, recurrent layers,
CTC loss over unaligned label sequences; `arch_deepspeech.py`).

Port on synthetic spectrograms: each "phoneme" is a band-limited energy
burst, utterances are unaligned phoneme sequences, and the model must
learn the alignment itself — exactly CTC's job. Conv frontend -> BiGRU
-> per-frame softmax -> CTCLoss, greedy CTC decode for eval.

    python example/speech_recognition/train_speech.py [--epochs 15]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn, rnn

N_MEL = 16          # spectrogram bins
FRAMES = 32         # time frames
N_PHONE = 5         # phoneme classes 0..4; CTC blank = index N_PHONE (last)
MAX_LABEL = 3


class SpeechNet(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.conv = nn.Conv1D(24, 5, padding=2, activation="relu",
                                  in_channels=N_MEL)
            self.gru = rnn.GRU(32, bidirectional=True, layout="NTC",
                               input_size=24)
            self.out = nn.Dense(N_PHONE + 1, flatten=False, in_units=64)

    def hybrid_forward(self, F, spec):
        # spec: (B, N_MEL, T)
        h = self.conv(spec).transpose((0, 2, 1))   # (B, T, C)
        return self.out(self.gru(h))               # (B, T, N_PHONE+1)


def make_utterances(n, rng):
    specs = rng.normal(0, 0.3, (n, N_MEL, FRAMES)).astype(np.float32)
    # pad with -1: gluon CTCLoss convention (labels < 0 mark padding;
    # blank is the LAST class index)
    labels = np.full((n, MAX_LABEL), -1.0, np.float32)
    for i in range(n):
        k = rng.integers(2, MAX_LABEL + 1)
        phones = rng.integers(0, N_PHONE, k)
        # spread bursts over time with jitter (unaligned!)
        starts = np.sort(rng.choice(FRAMES - 8, k, replace=False))
        for j, ph in enumerate(phones):
            band = slice(ph * 3, ph * 3 + 3)
            t0 = starts[j]
            specs[i, band, t0:t0 + 6] += 2.0
        labels[i, :k] = phones
    return specs, labels


def greedy_decode(logits):
    """CTC greedy: argmax per frame, collapse repeats, drop blanks
    (blank = N_PHONE, the last class)."""
    path = logits.argmax(-1)
    out = []
    for seq in path:
        prev, dec = -1, []
        for t in seq:
            if t != prev and t != N_PHONE:
                dec.append(int(t))
            prev = t
        out.append(dec)
    return out


def train(epochs=15, batch=32, lr=1e-2, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = SpeechNet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    X, Y = make_utterances(256, rng)
    Xv, Yv = make_utterances(96, rng)
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            with ag.record():
                logits = net(nd.array(X[i:i + batch]))
                loss = ctc(logits, nd.array(Y[i:i + batch])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        decoded = greedy_decode(net(nd.array(Xv)).asnumpy())
        exact = 0
        for d, lab in zip(decoded, Yv):
            ref = [int(v) for v in lab if v >= 0]
            exact += d == ref
        ser = 1.0 - exact / len(Yv)
        log("epoch %2d  ctc loss %.4f  seq err %.3f"
            % (ep, tot / (len(X) // batch), ser))
    return ser


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    train(epochs=ap.parse_args().epochs)
