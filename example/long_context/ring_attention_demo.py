"""Long-context attention via sequence parallelism (ring attention).

Demonstrates the framework's long-sequence scaling path (SURVEY §5.7 marks
this beyond-reference): queries/keys/values are sharded along the sequence
axis of an ``sp`` mesh; K/V blocks rotate around the ring with
``ppermute`` while every chip accumulates its query block's softmax
online — peak activation memory per chip is O(seq/sp) instead of O(seq),
and the attention matmuls stay on the MXU at full tile size.

On a pod, sp=16 puts a 512K-token context within per-chip HBM. This demo
runs the same code path on the virtual CPU mesh:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python ring_attention_demo.py --seq 4096
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--causal", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from mxnet_tpu import parallel

    n = len(jax.devices())
    sp = n
    mesh = parallel.make_mesh(dp=1, sp=sp)
    print("mesh: sp=%d over %s" % (sp, jax.devices()[0].platform))

    rng = np.random.RandomState(0)
    shape = (1, args.heads, args.seq, args.dim)
    q = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.5)
    k = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.5)
    v = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.5)

    t0 = time.time()
    out = parallel.ring_attention_sharded(q, k, v, mesh,
                                          causal=args.causal)
    out_h = np.asarray(out)
    t_ring = time.time() - t0
    print("ring attention: seq=%d, %d-way sequence parallel, %.2fs "
          "(first call includes compile)" % (args.seq, sp, t_ring))
    print("per-chip K/V block: %d tokens (%.1f%% of full sequence)"
          % (args.seq // sp, 100.0 / sp))

    # dense oracle on one device (only feasible at demo sizes)
    scale = 1.0 / np.sqrt(args.dim)
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) * scale
    if args.causal:
        s = np.where(np.tril(np.ones((args.seq, args.seq), bool)), s,
                     -np.inf)
    e = np.exp(s - s.max(-1, keepdims=True))
    ref = np.einsum("bhqk,bhkd->bhqd", e / e.sum(-1, keepdims=True),
                    np.asarray(v))
    err = np.abs(out_h - ref).max()
    print("max |ring - dense| = %.2e" % err)
    assert err < 2e-4, "ring attention diverges from dense oracle"
    print("OK")


if __name__ == "__main__":
    main()
