"""Stochastic Gradient Langevin Dynamics (reference
`example/bayesian-methods/sgld.ipynb` + `algos.py` — SGLD posterior
sampling: SGD steps plus Gaussian noise scaled to the step size, samples
collected after burn-in approximate the Bayesian posterior).

Port on Bayesian linear regression where the exact posterior is known in
closed form: the test asserts the SGLD sample mean matches the
analytical posterior mean and that the sample spread is nonzero (it is a
SAMPLER, not an optimizer). Exercises the optimizer extension surface —
SGLD is registered as a custom mx.optimizer.Optimizer.

    python example/bayesian-methods/sgld.py [--steps 4000]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, nd
from mxnet_tpu import optimizer as opt


@opt.register
class SGLDToy(opt.Optimizer):
    """reference algos.py SGLD: w += -lr/2 * grad + N(0, lr)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad + wd * weight
        noise = nd.random.normal(0, np.sqrt(lr), weight.shape)
        weight[:] = weight - 0.5 * lr * g + noise


def train(steps=4000, burn_in=1000, lr=2e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    # y = X w* + eps, eps ~ N(0, sigma^2); prior w ~ N(0, tau^2 I)
    n, d, sigma, tau = 64, 3, 0.5, 10.0
    w_true = np.array([1.5, -2.0, 0.5], np.float32)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = X @ w_true + sigma * rng.standard_normal(n).astype(np.float32)
    # closed-form posterior: Sigma = (X'X/sig^2 + I/tau^2)^-1, mu = Sigma X'y/sig^2
    Sigma = np.linalg.inv(X.T @ X / sigma ** 2 + np.eye(d) / tau ** 2)
    mu_post = Sigma @ X.T @ y / sigma ** 2

    w = nd.zeros((d,))
    w.attach_grad()
    optimizer = opt.create("sgldtoy", learning_rate=lr, rescale_grad=1.0)
    updater = opt.get_updater(optimizer)
    samples = []
    for t in range(steps):
        with ag.record():
            # negative log joint (up to const): lik + prior
            resid = nd.dot(nd.array(X), w) - nd.array(y)
            nll = (resid ** 2).sum() / (2 * sigma ** 2) + \
                (w ** 2).sum() / (2 * tau ** 2)
        nll.backward()
        updater(0, w.grad, w)
        if t >= burn_in and t % 10 == 0:
            samples.append(w.asnumpy().copy())
        if t % 1000 == 0:
            log("step %5d  nll %.2f" % (t, float(nll.asnumpy())))
    S = np.stack(samples)
    log("posterior mean (sgld): %s" % S.mean(0))
    log("posterior mean (true): %s" % mu_post)
    return S, mu_post, Sigma


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4000)
    train(steps=ap.parse_args().steps)
