"""Multi-task learning (reference `example/multi-task/example_multi_task.py`
— one trunk, two softmax heads trained jointly with a combined loss and
per-task metrics).

Port: shared conv trunk on synthetic digit images; head A classifies the
digit (10-way), head B classifies parity (2-way). The joint gradient
flows through the shared trunk from both heads in one backward.

    python example/multi-task/multitask.py [--epochs 8]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

SIZE = 16


class MultiTaskNet(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.trunk = nn.HybridSequential(prefix="trunk_")
            self.trunk.add(
                nn.Conv2D(8, 3, padding=1, activation="relu", in_channels=1),
                nn.MaxPool2D(2, 2),
                nn.Conv2D(16, 3, padding=1, activation="relu", in_channels=8),
                nn.MaxPool2D(2, 2),
                nn.Flatten(),
                nn.Dense(64, activation="relu"))
            self.head_digit = nn.Dense(10, in_units=64, prefix="digit_")
            self.head_parity = nn.Dense(2, in_units=64, prefix="parity_")

    def hybrid_forward(self, F, x):
        h = self.trunk(x)
        return self.head_digit(h), self.head_parity(h)


def make_digits(n, rng):
    """Blocky synthetic 'digits': digit d = d+1 bright cells on a fixed
    grid pattern, plus noise."""
    X = rng.normal(0, 0.2, (n, 1, SIZE, SIZE)).astype(np.float32)
    y = rng.integers(0, 10, n)
    cells = [(r, c) for r in range(2) for c in range(5)]
    for i in range(n):
        for j in range(y[i] + 1):
            r, c = cells[j % 10]
            X[i, 0, 2 + r * 7:7 + r * 7, 1 + c * 3:3 + c * 3] += 1.5
    return X, y.astype(np.float32), (y % 2).astype(np.float32)


def train(epochs=8, batch=32, lr=2e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = MultiTaskNet()
    net.initialize(mx.init.Xavier())
    X, Yd, Yp = make_digits(512, rng)
    Xv, Ydv, Ypv = make_digits(128, rng)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            with ag.record():
                od, op = net(nd.array(X[i:i + batch]))
                loss = loss_fn(od, nd.array(Yd[i:i + batch])).mean() + \
                    loss_fn(op, nd.array(Yp[i:i + batch])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        od, op = net(nd.array(Xv))
        acc_d = float((od.asnumpy().argmax(1) == Ydv).mean())
        acc_p = float((op.asnumpy().argmax(1) == Ypv).mean())
        log("epoch %d  loss %.4f  digit acc %.3f  parity acc %.3f"
            % (ep, tot / (len(X) // batch), acc_d, acc_p))
    return acc_d, acc_p


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    train(epochs=ap.parse_args().epochs)
