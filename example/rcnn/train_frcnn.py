"""Two-stage detector slice: RPN + Proposal + ROIAlign + classifier head.

Role parity: reference `example/rcnn/` (Faster R-CNN built on
_contrib_Proposal / _contrib_ROIAlign). Synthetic task: each image holds
one bright axis-aligned square (class 0) or a bright cross (class 1); the
RPN learns objectness + box regression over pixel-space anchors, Proposal
decodes + NMS's candidate boxes, ROIAlign pools their features, and a
small head classifies the pooled region.

RPN targets come from MultiBoxTarget with variances=(1,1,1,1) so the
encoding matches Proposal's unit-variance decode.

Usage:  python train_frcnn.py [--steps 60]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

IMAGE = 32
STRIDE = 4
SCALES = (2, 3)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)


def pixel_anchors():
    """The exact anchor grid Proposal enumerates, normalized to [0, 1]
    (ratio-major/scale-minor, (a, h, w) flatten order)."""
    from mxnet_tpu.ops.proposal_ops import _gen_base_anchors
    import jax.numpy as jnp
    F = IMAGE // STRIDE
    base = np.asarray(_gen_base_anchors(STRIDE, RATIOS, SCALES,
                                        jnp.float32))
    sy = np.arange(F) * STRIDE
    sx = np.arange(F) * STRIDE
    out = np.zeros((A, F, F, 4), "float32")
    for a in range(A):
        for i, y in enumerate(sy):
            for j, x in enumerate(sx):
                out[a, i, j] = base[a] + [x, y, x, y]
    return out.reshape(1, -1, 4) / IMAGE


def synthetic_batch(batch, rng):
    x = rng.rand(batch, 1, IMAGE, IMAGE).astype("float32") * 0.1
    labels = np.zeros((batch, 1, 5), "float32")
    for b in range(batch):
        cls = rng.randint(0, 2)
        size = rng.randint(8, 14)
        cy, cx = rng.randint(size // 2 + 1, IMAGE - size // 2 - 1, 2)
        y1, y2 = cy - size // 2, cy + size // 2
        x1, x2 = cx - size // 2, cx + size // 2
        if cls == 0:
            x[b, 0, y1:y2, x1:x2] = 1.0          # filled square
        else:
            x[b, 0, cy - 1:cy + 1, x1:x2] = 1.0  # cross
            x[b, 0, y1:y2, cx - 1:cx + 1] = 1.0
        labels[b, 0] = [cls, x1 / IMAGE, y1 / IMAGE, x2 / IMAGE, y2 / IMAGE]
    return mx.nd.array(x), mx.nd.array(labels)


class FRCNN(gluon.Block):
    def __init__(self, num_classes=2, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.backbone = gluon.nn.Sequential()
            for ch in (16, 32):
                self.backbone.add(gluon.nn.Conv2D(ch, 3, padding=1),
                                  gluon.nn.Activation("relu"),
                                  gluon.nn.MaxPool2D(2))
            self.rpn_conv = gluon.nn.Conv2D(32, 3, padding=1,
                                            activation="relu")
            self.rpn_cls = gluon.nn.Conv2D(2 * A, 1)
            self.rpn_loc = gluon.nn.Conv2D(4 * A, 1)
            self.head = gluon.nn.Sequential()
            self.head.add(gluon.nn.Dense(32, activation="relu"),
                          gluon.nn.Dense(num_classes))

    def rpn(self, x):
        feat = self.backbone(x)
        r = self.rpn_conv(feat)
        return feat, self.rpn_cls(r), self.rpn_loc(r)

    def propose(self, cls_score, loc, post_nms=8):
        B = cls_score.shape[0]
        F = cls_score.shape[2]
        # softmax over the (bg, fg) pair per anchor
        s = cls_score.reshape((B, 2, A, F, F))
        probs = mx.nd.softmax(s, axis=1).reshape((B, 2 * A, F, F))
        im_info = mx.nd.array(np.tile([IMAGE, IMAGE, 1.0], (B, 1))
                              .astype("float32"))
        rois, scores = mx.nd.contrib.MultiProposal(
            probs, loc, im_info, rpn_pre_nms_top_n=32,
            rpn_post_nms_top_n=post_nms, threshold=0.7, rpn_min_size=4,
            scales=SCALES, ratios=RATIOS, feature_stride=STRIDE,
            output_score=True)
        return rois, scores

    def classify(self, feat, rois):
        pooled = mx.nd.contrib.ROIAlign(
            feat, rois, pooled_size=(4, 4), spatial_scale=1.0 / STRIDE)
        return self.head(pooled.reshape((pooled.shape[0], -1)))


def train(steps=60, batch=8, lr=0.02, log=print):
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = FRCNN()
    net.initialize(mx.init.Xavier())
    anchors = mx.nd.array(pixel_anchors())
    xb, yb = synthetic_batch(batch, rng)
    feat, c, l = net.rpn(xb)
    net.classify(feat, mx.nd.array(np.array([[0, 4, 4, 20, 20]],
                                            "float32")))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    huber = gluon.loss.HuberLoss()

    first = last = None
    for step in range(steps):
        xb, yb = synthetic_batch(batch, rng)
        with ag.record():
            feat, cls_score, loc = net.rpn(xb)
            B, _, F, _ = cls_score.shape
            # (a, h, w) flatten order to match the anchor grid
            cls_ahw = cls_score.reshape((B, 2, A, F, F)) \
                               .transpose((0, 1, 2, 3, 4)) \
                               .reshape((B, 2, -1))
            loc_ahw = loc.reshape((B, A, 4, F, F)) \
                         .transpose((0, 1, 3, 4, 2)).reshape((B, -1))
            bt, bm, ct = mx.nd.contrib.MultiBoxTarget(
                anchors, yb, cls_ahw, overlap_threshold=0.5,
                variances=(1.0, 1.0, 1.0, 1.0))
            obj = (ct > 0).astype("float32")  # class-agnostic objectness
            rpn_cls_l = ce(cls_ahw.transpose((0, 2, 1)).reshape((-1, 2)),
                           obj.reshape((-1,)))
            rpn_loc_l = huber(loc_ahw * bm, bt * bm)
            # head training on ground-truth boxes (pixel coords)
            gt_rois = mx.nd.concat(
                mx.nd.arange(B).reshape((B, 1)),
                yb[:, 0, 1:5] * IMAGE, dim=1)
            logits = net.classify(feat, gt_rois)
            head_l = ce(logits, yb[:, 0, 0])
            loss = rpn_cls_l.mean() + rpn_loc_l.mean() + head_l.mean()
        loss.backward()
        trainer.step(batch)
        last = float(loss.asnumpy())
        first = last if first is None else first
        if step % 10 == 0:
            log("step %3d  loss %.4f (rpn_cls %.3f loc %.3f head %.3f)"
                % (step, last, float(rpn_cls_l.mean().asnumpy()),
                   float(rpn_loc_l.mean().asnumpy()),
                   float(head_l.mean().asnumpy())))
    return net, first, last


def evaluate(net, n=8):
    """Proposal quality + classification accuracy on fresh scenes."""
    rng = np.random.RandomState(1)
    xb, yb = synthetic_batch(n, rng)
    feat, cls_score, loc = net.rpn(xb)
    rois, scores = net.propose(cls_score, loc)
    r = rois.asnumpy()
    gt = yb.asnumpy()[:, 0, 1:5] * IMAGE
    best_iou = []
    for b in range(n):
        mine = r[r[:, 0] == b][:, 1:]
        g = gt[b]
        ious = []
        for m in mine:
            ix = max(0, min(m[2], g[2]) - max(m[0], g[0]))
            iy = max(0, min(m[3], g[3]) - max(m[1], g[1]))
            inter = ix * iy
            u = ((m[2] - m[0]) * (m[3] - m[1]) +
                 (g[2] - g[0]) * (g[3] - g[1]) - inter)
            ious.append(inter / u if u > 0 else 0.0)
        best_iou.append(max(ious) if ious else 0.0)
    gt_rois = mx.nd.concat(
        mx.nd.arange(n).reshape((n, 1)),
        yb[:, 0, 1:5] * IMAGE, dim=1)
    logits = net.classify(feat, gt_rois).asnumpy()
    acc = (logits.argmax(1) == yb.asnumpy()[:, 0, 0]).mean()
    return float(np.mean(best_iou)), float(acc)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    args = ap.parse_args()
    net, first, last = train(args.steps)
    print("loss: %.4f -> %.4f" % (first, last))
    miou, acc = evaluate(net)
    print("mean best-proposal IoU: %.3f   head accuracy: %.2f"
          % (miou, acc))


if __name__ == "__main__":
    main()
