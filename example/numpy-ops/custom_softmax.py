"""Training with a numpy-implemented custom operator.

Role parity: reference `example/numpy-ops/custom_softmax.py`: the softmax
loss layer is replaced by a user-written CustomOp whose forward and
backward are plain numpy, registered with `mx.operator.register`, then
used inside a symbol graph and trained with Module — the "extend the
framework from Python without touching the engine" demo.

TPU-native notes: custom ops run as host callbacks outside the XLA
program (the reference's CustomOp runs on CPU outside the engine's
threads, same topology). Everything surrounding the custom node still
compiles to XLA; only the custom segment round-trips to host. Use this
for experimentation; promote hot ops to `mxnet_tpu.ops` (jnp/pallas) for
production speed.

Usage:  python custom_softmax.py [--epochs 6]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


class NumpySoftmax(mx.operator.CustomOp):
    """Softmax + cross-entropy gradient, all in numpy (reference
    example/numpy-ops/custom_softmax.py Softmax)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        x = x - x.max(axis=1, keepdims=True)
        e = np.exp(x)
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        label = in_data[1].asnumpy().astype(int)
        p = out_data[0].asnumpy().copy()
        p[np.arange(p.shape[0]), label] -= 1.0
        # per-sample gradient; Module's rescale_grad divides by batch
        self.assign(in_grad[0], req[0], p)


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return NumpySoftmax()


def net_symbol(classes=10):
    data = sym.var("data")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=32, name="fc1"),
                       act_type="relu")
    logits = sym.FullyConnected(h, num_hidden=classes, name="fc2")
    label = sym.var("softmax_label")
    return sym.Custom(logits, label, op_type="numpy_softmax",
                      name="softmax")


def train(epochs=6, n=512, in_dim=16, classes=10, log=print):
    rng = np.random.RandomState(0)
    w = rng.randn(in_dim, classes).astype("float32")
    x = rng.randn(n, in_dim).astype("float32")
    y = (x @ w).argmax(axis=1).astype("float32")

    it = mx.io.NDArrayIter(x, y, batch_size=64, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(net_symbol(classes), context=mx.cpu(),
                        data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(it, eval_metric="acc", optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(), num_epoch=epochs)
    it.reset()
    acc = dict(mod.score(it, "acc"))["accuracy"]
    log("custom-op training accuracy %.3f" % acc)
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()
    train(epochs=args.epochs)
