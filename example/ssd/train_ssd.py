"""Single-shot detector training on synthetic scenes.

Role parity: reference `example/ssd/` (SSD training driver built on
_contrib_MultiBoxPrior / MultiBoxTarget / MultiBoxDetection). A compact
single-scale SSD: conv backbone -> (cls, loc) heads over per-pixel anchors,
target assignment by the MultiBoxTarget op, SmoothL1 + softmax CE loss,
decode + NMS by MultiBoxDetection at eval.

Usage:  python train_ssd.py [--steps 50] [--image 64]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


class TinySSD(gluon.Block):
    """Backbone + single-scale multibox heads (A anchors per position)."""

    def __init__(self, num_classes=2, sizes=(0.3, 0.5), ratios=(1.0, 2.0),
                 **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.num_anchors = len(sizes) + len(ratios) - 1
        self._sizes, self._ratios = sizes, ratios
        with self.name_scope():
            self.backbone = gluon.nn.Sequential()
            for ch in (16, 32, 64):
                self.backbone.add(gluon.nn.Conv2D(ch, 3, padding=1),
                                  gluon.nn.BatchNorm(),
                                  gluon.nn.Activation("relu"),
                                  gluon.nn.MaxPool2D(2))
            self.cls_head = gluon.nn.Conv2D(
                self.num_anchors * (num_classes + 1), 3, padding=1)
            self.loc_head = gluon.nn.Conv2D(self.num_anchors * 4, 3,
                                            padding=1)

    def forward(self, x):
        feat = self.backbone(x)
        anchors = mx.nd.contrib.MultiBoxPrior(feat, sizes=self._sizes,
                                              ratios=self._ratios)
        B = x.shape[0]
        # heads -> (B, N_anchors, ...) layouts the MultiBox ops expect
        cls = self.cls_head(feat).transpose((0, 2, 3, 1)).reshape(
            (B, -1, self.num_classes + 1))
        loc = self.loc_head(feat).transpose((0, 2, 3, 1)).reshape((B, -1))
        return anchors, cls, loc


def synthetic_batch(batch, image, rng):
    """One box per image: a bright square on dark background, class 0."""
    x = rng.rand(batch, 3, image, image).astype("float32") * 0.1
    labels = np.zeros((batch, 1, 5), "float32")
    for b in range(batch):
        cx, cy = rng.rand(2) * 0.5 + 0.25
        s = 0.2 + rng.rand() * 0.15
        x1, y1 = max(cx - s / 2, 0), max(cy - s / 2, 0)
        x2, y2 = min(cx + s / 2, 1), min(cy + s / 2, 1)
        labels[b, 0] = [0, x1, y1, x2, y2]
        px = slice(int(y1 * image), max(int(y2 * image), int(y1 * image) + 1))
        py = slice(int(x1 * image), max(int(x2 * image), int(x1 * image) + 1))
        x[b, :, px, py] = 1.0
    return mx.nd.array(x), mx.nd.array(labels)


def train(steps=50, batch=8, image=64, lr=0.05, log=print):
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = TinySSD()
    net.initialize(mx.init.Xavier())
    xb, yb = synthetic_batch(batch, image, rng)
    net(xb)  # resolve deferred shapes
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    smooth_l1 = gluon.loss.HuberLoss()

    first = last = None
    for step in range(steps):
        xb, yb = synthetic_batch(batch, image, rng)
        with ag.record():
            anchors, cls, loc = net(xb)
            bt, bm, ct = mx.nd.contrib.MultiBoxTarget(
                anchors, yb, cls.transpose((0, 2, 1)),
                negative_mining_ratio=3.0)
            cls_l = ce(cls.reshape((-1, cls.shape[-1])), ct.reshape((-1,)))
            loc_l = smooth_l1(loc * bm, bt * bm)
            loss = cls_l.mean() + loc_l.mean()
        loss.backward()
        trainer.step(batch)
        v = float(loss.asnumpy())
        first = v if first is None else first
        last = v
        if step % 10 == 0:
            log("step %3d  loss %.4f" % (step, v))
    return net, first, last


def detect(net, x, threshold=0.3):
    anchors, cls, loc = net(x)
    probs = mx.nd.softmax(cls, axis=-1).transpose((0, 2, 1))
    return mx.nd.contrib.MultiBoxDetection(probs, loc, anchors,
                                           threshold=threshold)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--image", type=int, default=64)
    args = ap.parse_args()
    net, first, last = train(args.steps, args.batch, args.image)
    print("loss: %.4f -> %.4f" % (first, last))
    rng = np.random.RandomState(1)
    xb, yb = synthetic_batch(2, args.image, rng)
    out = detect(net, xb).asnumpy()
    kept = out[0][out[0, :, 0] >= 0]
    print("detections (img 0): %d, best score %.3f"
          % (kept.shape[0], kept[:, 1].max() if kept.size else 0.0))
    print("gt box:", yb.asnumpy()[0, 0, 1:])
    if kept.size:
        print("top box:", kept[np.argmax(kept[:, 1]), 2:6])


if __name__ == "__main__":
    main()
