"""Profiling a training loop with the mx.profiler API.

Role parity: reference `example/profiler/profiler_executor.py` /
`profiler_ndarray.py`: turn the profiler on around a training region,
dump, and read where the time went.

TPU-native notes: `mx.profiler` fronts jax.profiler — the dump is an
XPlane trace (view in TensorBoard or Perfetto) containing XLA fusion
timings on the device, not per-op host timings: under XLA the unit of
execution IS the fused program (this produced PERF.md's profiler study).
Custom scopes land in the trace via `profiler.scope`/`record_function`.

Usage:  python profile_training.py [--steps 30] [--outdir /tmp/mxtpu_prof]
"""
import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def train_profiled(steps=30, outdir="/tmp/mxtpu_prof", log=print):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = rng.randn(steps, 64, 32).astype("float32")
    y = rng.randint(0, 10, (steps, 64)).astype("float32")

    # warm up OUTSIDE the profiled region so the trace holds steady-state
    # steps, not compiles (reference examples skip the first batch too)
    with ag.record():
        loss = loss_fn(net(nd.array(x[0])), nd.array(y[0])).mean()
    loss.backward()
    trainer.step(1)
    loss.asnumpy()

    mx.profiler.set_config(profile_all=True,
                           filename=os.path.join(outdir, "profile.json"))
    mx.profiler.set_state("run")
    for i in range(steps):
        with ag.record():
            loss = loss_fn(net(nd.array(x[i])), nd.array(y[i])).mean()
        loss.backward()
        trainer.step(1)
    loss.asnumpy()          # drain before stopping the trace
    mx.profiler.set_state("stop")
    mx.profiler.dump()

    traces = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                       recursive=True) + \
        glob.glob(os.path.join(outdir, "**", "*.trace.json*"),
                  recursive=True)
    log("profiled %d steps -> %d trace file(s) under %s"
        % (steps, len(traces), outdir))
    for t in traces[:3]:
        log("  ", t, os.path.getsize(t), "bytes")
    return traces


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--outdir", default="/tmp/mxtpu_prof")
    args = ap.parse_args()
    train_profiled(steps=args.steps, outdir=args.outdir)
