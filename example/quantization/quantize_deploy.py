"""Post-training INT8 quantization: train float -> calibrate -> deploy.

Role parity: reference `example/quantization/` (imagenet_gen_qsym_mkldnn /
imagenet_inference): take a trained FP32 network, run calibration batches
to freeze activation ranges, swap compute to int8, compare accuracy
against the float model, and persist the quantized model for deployment.

TPU-native notes: the int8 path runs real int8 x int8 -> int32 matmul/
conv on the MXU (`ops/quantized_ops.py`); ranges travel as (1,) tensors.
Calibrated ranges, int8 weights and scales are registered Parameters, so
`save_parameters`/`load_parameters` carries the whole deployable artifact
(no re-calibration at load time).

Usage:  python quantize_deploy.py [--epochs 3]
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.contrib.quantization import quantize_net

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def build_cnn(classes=10):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1,
                            activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, kernel_size=3, padding=1,
                            activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(classes))
    return net


def make_data(n=512, classes=10, seed=0):
    """Tiny image-like task: class = dominant quadrant pattern."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = rng.randn(n, 1, 12, 12).astype("float32") * 0.3
    for i, c in enumerate(y):
        r, col = divmod(c, 4)
        x[i, 0, r * 3:(r + 1) * 3, col * 3:(col + 1) * 3] += 2.0
    return x, y.astype("float32")


def accuracy(net, x, y, batch=64):
    correct = 0
    for s in range(0, len(y), batch):
        out = net(nd.array(x[s:s + batch])).asnumpy()
        correct += int((out.argmax(1) == y[s:s + batch]).sum())
    return correct / len(y)


def train_float(net, x, y, epochs, batch=64, log=print):
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(epochs):
        total = 0.0
        for s in range(0, len(y), batch):
            xb, yb = nd.array(x[s:s + batch]), nd.array(y[s:s + batch])
            with ag.record():
                loss = loss_fn(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy())
        log("epoch %d loss %.4f" % (epoch, total / (len(y) // batch)))


def main(epochs=3, log=print):
    x, y = make_data()
    x_cal, y_cal = make_data(n=128, seed=1)   # calibration split
    x_test, y_test = make_data(n=256, seed=2)

    net = build_cnn()
    train_float(net, x, y, epochs, log=log)
    acc_fp32 = accuracy(net, x_test, y_test)
    log("fp32 accuracy %.3f" % acc_fp32)

    # calibrate on held-out batches, freeze ranges, swap to int8
    calib = [nd.array(x_cal[s:s + 64]) for s in range(0, 128, 64)]
    qnet = quantize_net(net, calib_data=calib, calib_mode="naive")
    acc_int8 = accuracy(qnet, x_test, y_test)
    log("int8 accuracy %.3f (drop %.3f)" % (acc_int8, acc_fp32 - acc_int8))

    # deploy: persist the quantized artifact, reload into a FRESH net
    path = os.path.join(tempfile.gettempdir(), "quantized_cnn.params")
    qnet.save_parameters(path)
    net2 = build_cnn()
    net2.initialize(mx.init.Xavier())
    net2(nd.array(x[:1]))                    # shape the params
    qnet2 = quantize_net(net2)               # uncalibrated swap
    qnet2.load_parameters(path)              # ranges+weights from file
    acc_loaded = accuracy(qnet2, x_test, y_test)
    log("reloaded int8 accuracy %.3f" % acc_loaded)
    return acc_fp32, acc_int8, acc_loaded


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()
    main(epochs=args.epochs)
