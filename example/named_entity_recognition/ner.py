"""Named-entity recognition (reference
`example/named_entity_recognition/src/ner.py` — BiLSTM over embedded
tokens, per-token softmax, entity-weighted loss on padded sequences).

Synthetic entity data: PERSON tokens follow a trigger token ("mr"),
LOCATION tokens follow "in" — so the tagger must use LEFT context
(forward LSTM) while plain per-token classification fails; a second
pattern needs RIGHT context (backward LSTM). Padding is masked out of
the loss with SequenceMask like the reference's sample weighting.

    python example/named_entity_recognition/ner.py [--epochs 10]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn, rnn

VOCAB, MAXLEN, EMBED, HIDDEN = 60, 14, 16, 32
TAGS = 3            # O / PERSON / LOCATION
MR, IN = 5, 6       # trigger tokens
PAD = 0


class NERTagger(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, EMBED)
            self.lstm = rnn.LSTM(HIDDEN, bidirectional=True,
                                 layout="NTC", input_size=EMBED)
            self.out = nn.Dense(TAGS, flatten=False, in_units=2 * HIDDEN)

    def hybrid_forward(self, F, tokens):
        e = self.embed(tokens)          # (B, T, E)
        h = self.lstm(e)                # (B, T, 2H)
        return self.out(h)              # (B, T, TAGS)


def make_data(n, rng):
    X = rng.integers(10, VOCAB, (n, MAXLEN))
    Y = np.zeros((n, MAXLEN), np.int64)
    lengths = rng.integers(8, MAXLEN + 1, n)
    for i in range(n):
        X[i, lengths[i]:] = PAD
        # "mr <PERSON>" somewhere
        p = rng.integers(0, lengths[i] - 2)
        X[i, p] = MR
        Y[i, p + 1] = 1
        # "<LOC> in" (right-context pattern: the entity PRECEDES it)
        q = rng.integers(0, lengths[i] - 2)
        if abs(int(q) - int(p)) > 2:
            X[i, q + 1] = IN
            Y[i, q] = 2
    return (X.astype(np.float32), Y.astype(np.float32),
            lengths.astype(np.float32))


def train(epochs=10, batch=32, lr=5e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = NERTagger()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    X, Y, L = make_data(512, rng)
    Xv, Yv, Lv = make_data(128, rng)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            xb, yb = nd.array(X[i:i + batch]), nd.array(Y[i:i + batch])
            lb = nd.array(L[i:i + batch])
            with ag.record():
                out = net(xb)                                  # (B,T,C)
                # per-token NLL, padding masked out (reference ner.py
                # weights the loss by a not-pad mask)
                logp = nd.log_softmax(out, axis=-1)
                per_tok = -nd.pick(logp, yb, axis=-1)          # (B,T)
                masked = nd.SequenceMask(per_tok.transpose((1, 0)),
                                         sequence_length=lb,
                                         use_sequence_length=True)
                loss = masked.sum() / lb.sum()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        pred = net(nd.array(Xv)).asnumpy().argmax(-1)
        mask = (np.arange(MAXLEN)[None] < Lv[:, None])
        ent = (Yv > 0) & mask
        ent_recall = float((pred[ent] == Yv[ent]).mean())
        acc = float((pred[mask] == Yv[mask]).mean())
        log("epoch %d  loss %.4f  tok acc %.3f  entity recall %.3f"
            % (ep, tot / (len(X) // batch), acc, ent_recall))
    return acc, ent_recall


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    train(epochs=ap.parse_args().epochs)
