"""CTC sequence training: BiLSTM + CTC loss on synthetic OCR-style data.

Role parity: reference `example/ctc/lstm_ocr_train.py` (captcha OCR with
warp-CTC / mx.sym.ctc_loss). Synthetic task: each "image" is a sequence of
column vectors, each column one-hot-ish for a digit with noise; the label
is the digit string without blanks or repeats collapsed — exactly the CTC
alignment problem.

Usage:  python lstm_ocr.py [--steps 80]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

NUM_CLASSES = 10  # digits; CTC blank is class NUM_CLASSES


def synthetic_batch(batch, seq_len, label_len, rng):
    """Each label digit is painted over a random span of columns."""
    x = rng.rand(batch, seq_len, NUM_CLASSES).astype("float32") * 0.3
    labels = np.zeros((batch, label_len), "float32")
    for b in range(batch):
        digits = rng.randint(0, NUM_CLASSES, label_len)
        labels[b] = digits
        # paint digits over consecutive spans
        bounds = np.sort(rng.choice(
            np.arange(1, seq_len), label_len - 1, replace=False))
        spans = np.split(np.arange(seq_len), bounds)
        for d, span in zip(digits, spans):
            x[b, span, d] += 2.0
    return mx.nd.array(x), mx.nd.array(labels)


class CTCNet(gluon.Block):
    def __init__(self, hidden=32, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.lstm = gluon.rnn.LSTM(hidden, bidirectional=True,
                                       layout="NTC")
            self.proj = gluon.nn.Dense(NUM_CLASSES + 1, flatten=False)

    def forward(self, x):
        return self.proj(self.lstm(x))  # (B, T, C+1)


def train(steps=80, batch=16, seq_len=20, label_len=4, lr=0.02,
          log=print):
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = CTCNet()
    net.initialize(mx.init.Xavier())
    xb, yb = synthetic_batch(batch, seq_len, label_len, rng)
    net(xb)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    first = last = None
    for step in range(steps):
        xb, yb = synthetic_batch(batch, seq_len, label_len, rng)
        with ag.record():
            logits = net(xb)
            loss = ctc(logits, yb).mean()
        loss.backward()
        trainer.step(batch)
        last = float(loss.asnumpy())
        first = last if first is None else first
        if step % 10 == 0:
            log("step %3d  ctc loss %.4f" % (step, last))
    return net, first, last


def greedy_decode(logits):
    """Best-path decode: argmax per frame, collapse repeats, drop blanks."""
    ids = np.argmax(logits, axis=-1)
    out = []
    for row in ids:
        prev = -1
        s = []
        for t in row:
            if t != prev and t != NUM_CLASSES:
                s.append(int(t))
            prev = t
        out.append(s)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=80)
    args = ap.parse_args()
    net, first, last = train(args.steps)
    print("ctc loss: %.4f -> %.4f" % (first, last))
    rng = np.random.RandomState(1)
    xb, yb = synthetic_batch(4, 20, 4, rng)
    decoded = greedy_decode(net(xb).asnumpy())
    correct = sum(d == list(map(int, y)) for d, y in
                  zip(decoded, yb.asnumpy()))
    print("exact-sequence accuracy: %d/4" % correct)
    print("sample: predicted", decoded[0], "label",
          [int(v) for v in yb.asnumpy()[0]])


if __name__ == "__main__":
    main()
