"""Matrix-factorization recommender on a synthetic low-rank rating matrix.

Role parity: reference `example/recommenders/demo1-MF.ipynb` /
`example/module/matrix_factorization*.py` (user/item embeddings, dot
product score, MSE). The embedding gradient is dense here (SparseEmbedding
is the dense-fallback alias — SURVEY §5.9); on TPU the full embedding
update is one fused scatter inside the jitted step.

Usage:  python matrix_fact.py [--steps 200]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


class MFNet(gluon.Block):
    def __init__(self, num_users, num_items, factors=8, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = gluon.nn.Embedding(num_users, factors)
            self.item = gluon.nn.Embedding(num_items, factors)

    def forward(self, users, items):
        return (self.user(users) * self.item(items)).sum(axis=1)


def make_ratings(num_users=64, num_items=48, rank=4, seed=0):
    rng = np.random.RandomState(seed)
    u = rng.randn(num_users, rank) * 0.8
    v = rng.randn(num_items, rank) * 0.8
    return (u @ v.T).astype("float32"), rng


def train(steps=200, batch=256, factors=8, lr=0.1, log=print):
    mx.random.seed(0)
    ratings, rng = make_ratings()
    nu, ni = ratings.shape
    net = MFNet(nu, ni, factors)
    net.initialize(mx.init.Normal(0.1))
    net(mx.nd.array(np.zeros(2, "float32")),
        mx.nd.array(np.zeros(2, "float32")))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    l2 = gluon.loss.L2Loss()
    first = last = None
    for step in range(steps):
        us = rng.randint(0, nu, batch)
        its = rng.randint(0, ni, batch)
        r = mx.nd.array(ratings[us, its])
        with ag.record():
            pred = net(mx.nd.array(us.astype("float32")),
                       mx.nd.array(its.astype("float32")))
            loss = l2(pred, r).mean()
        loss.backward()
        trainer.step(batch)
        last = float(loss.asnumpy())
        first = last if first is None else first
        if step % 40 == 0:
            log("step %3d  mse %.4f" % (step, 2 * last))
    return net, ratings, first, last


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    args = ap.parse_args()
    net, ratings, first, last = train(args.steps)
    # full-matrix reconstruction error
    nu, ni = ratings.shape
    uu, ii = np.meshgrid(np.arange(nu), np.arange(ni), indexing="ij")
    pred = net(mx.nd.array(uu.ravel().astype("float32")),
               mx.nd.array(ii.ravel().astype("float32")))
    rmse = float(np.sqrt(np.mean(
        (pred.asnumpy() - ratings.ravel()) ** 2)))
    print("loss %.4f -> %.4f ; full-matrix RMSE %.4f (rating std %.3f)"
          % (first, last, rmse, ratings.std()))


if __name__ == "__main__":
    main()
