"""Sparse linear classification on libsvm-format data.

Role parity: reference `example/sparse/linear_classification/train.py`:
a linear model whose weight is ROW-SPARSE, fed by libsvm-format sparse
features; every step pulls only the weight rows the batch touches from the
kvstore (`kv.row_sparse_pull(..., row_ids=batch_cols)`), computes the
sparse dot, and pushes a row-sparse gradient back.

TPU-native notes: the compute itself is a dense matmul over the batch's
CSR rows scattered into a dense block (XLA has no CSR kernels; a gather +
MXU matmul wins on this hardware for the classic KDD-style shapes), while
the STORAGE and the kvstore traffic stay row-sparse — which is the part
the reference example exists to demonstrate.

Usage:  python linear_classification.py [--epochs 5]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def make_libsvm(path, n=512, feat=1000, active=12, seed=0):
    """Synthetic libsvm file: y in {0,1} from a sparse ground-truth w."""
    rng = np.random.RandomState(seed)
    w_true = np.zeros(feat, np.float32)
    support = rng.choice(feat, 40, replace=False)
    w_true[support] = rng.randn(40)
    with open(path, "w") as fh:
        for _ in range(n):
            cols = np.sort(rng.choice(feat, active, replace=False))
            vals = rng.rand(active).astype(np.float32) + 0.1
            y = 1 if float(vals @ w_true[cols]) > 0 else 0
            fh.write("%d %s\n" % (y, " ".join(
                "%d:%.4f" % (c, v) for c, v in zip(cols, vals))))
    return w_true


def load_libsvm(path, feat):
    """Parse libsvm rows into a CSR matrix + labels (the reference feeds
    this through LibSVMIter; parsing is the example's data code here)."""
    data, indices, indptr, labels = [], [], [0], []
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                c, v = tok.split(":")
                indices.append(int(c))
                data.append(float(v))
            indptr.append(len(indices))
    csr = sparse.csr_matrix(
        (np.asarray(data, np.float32), np.asarray(indices, np.int64),
         np.asarray(indptr, np.int64)), shape=(len(labels), feat))
    return csr, np.asarray(labels, np.float32)


def batches(csr, labels, batch_size):
    n = labels.shape[0]
    for s in range(0, n - batch_size + 1, batch_size):
        rows = csr[s:s + batch_size]
        # column ids this batch touches -> the row ids of the weight we
        # must pull (reference train.py sparse_row_id_fn)
        dense = rows.asnumpy()
        touched = np.nonzero(dense.any(axis=0))[0]
        yield dense, labels[s:s + batch_size], touched


def train(epochs=5, feat=1000, batch_size=64, lr=0.5, log=print):
    tmp = os.path.join("/tmp", "sparse_linear.libsvm")
    w_true = make_libsvm(tmp, feat=feat)
    csr, labels = load_libsvm(tmp, feat)

    # row-sparse weight lives in the kvstore, updated ON the store
    # (reference update_on_kvstore=True dist layout)
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=lr))
    weight = nd.zeros((feat, 1))
    bias = nd.zeros((1,))
    kv.init("w", weight)

    losses = []
    for epoch in range(epochs):
        total, count = 0.0, 0
        for x, y, touched in batches(csr, labels, batch_size):
            # pull ONLY the touched rows, row-sparse (reference
            # kvstore.row_sparse_pull on every forward)
            w_rs = sparse.row_sparse_array(
                (np.zeros((len(touched), 1), np.float32), touched),
                shape=(feat, 1))
            kv.row_sparse_pull("w", out=w_rs, row_ids=nd.array(touched))

            xb = nd.array(x)
            yb = nd.array(y)
            w_dense = nd.array(w_rs.asnumpy())
            w_dense.attach_grad()
            bias.attach_grad()
            with mx.autograd.record():
                logit = nd.dot(xb, w_dense) + bias
                p = nd.sigmoid(logit).reshape((batch_size,))
                eps = 1e-7
                loss = -(yb * nd.log(p + eps) +
                         (1 - yb) * nd.log(1 - p + eps)).mean()
            loss.backward()

            # push a ROW-SPARSE gradient: only touched rows move; the
            # store-side optimizer applies sgd (update_on_kvstore)
            g = w_dense.grad.asnumpy()
            g_rs = sparse.row_sparse_array(
                (g[touched], touched), shape=(feat, 1))
            kv.push("w", g_rs)
            kv.pull("w", out=weight)
            bias -= lr * bias.grad
            total += float(loss.asnumpy())
            count += 1
        losses.append(total / count)
        log("epoch %d: loss %.4f" % (epoch, losses[-1]))

    # final accuracy over the training set
    w_final = weight.asnumpy()
    logits = csr.asnumpy() @ w_final + bias.asnumpy()
    acc = float(((logits.ravel() > 0) == (labels > 0.5)).mean())
    log("train accuracy %.3f" % acc)
    return losses, acc, w_final, w_true


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    args = ap.parse_args()
    train(epochs=args.epochs)
