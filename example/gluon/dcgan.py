"""DCGAN on synthetic image data.

Role parity: reference `example/gluon/dcgan.py` (DCGAN with alternating
generator/discriminator SGD). Synthetic target distribution: images whose
lower half is bright and upper half is dark — easy to learn, easy to test.

Usage:  python dcgan.py [--steps 100]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def build_generator(ngf=16, nz=16):
    net = gluon.nn.Sequential()
    with net.name_scope():
        # z (B, nz, 1, 1) -> (B, 1, 16, 16)
        net.add(gluon.nn.Conv2DTranspose(ngf * 2, 4, strides=1, padding=0,
                                         use_bias=False),
                gluon.nn.BatchNorm(), gluon.nn.Activation("relu"),
                gluon.nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                         use_bias=False),
                gluon.nn.BatchNorm(), gluon.nn.Activation("relu"),
                gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                         use_bias=False),
                gluon.nn.Activation("tanh"))
    return net


def build_discriminator(ndf=16):
    net = gluon.nn.Sequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(ndf, 4, strides=2, padding=1,
                                use_bias=False),
                gluon.nn.LeakyReLU(0.2),
                gluon.nn.Conv2D(ndf * 2, 4, strides=2, padding=1,
                                use_bias=False),
                gluon.nn.BatchNorm(), gluon.nn.LeakyReLU(0.2),
                gluon.nn.Conv2D(1, 4, strides=1, padding=0,
                                use_bias=False))
    return net


def real_batch(batch, rng):
    """Images in [-1, 1]: bright lower half, dark upper half + noise."""
    x = rng.randn(batch, 1, 16, 16).astype("float32") * 0.1
    x[:, :, 8:, :] += 0.8
    x[:, :, :8, :] -= 0.8
    return mx.nd.array(np.clip(x, -1, 1))


def train(steps=100, batch=32, nz=16, lr=2e-4, log=print):
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    gen, dis = build_generator(nz=nz), build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    dis.initialize(mx.init.Normal(0.02))
    z0 = mx.nd.array(rng.randn(batch, nz, 1, 1).astype("float32"))
    dis(gen(z0))  # resolve deferred shapes
    gt = gluon.Trainer(gen.collect_params(), "adam",
                       {"learning_rate": lr, "beta1": 0.5})
    dt = gluon.Trainer(dis.collect_params(), "adam",
                       {"learning_rate": lr, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    ones = mx.nd.ones((batch,))
    zeros = mx.nd.zeros((batch,))

    d_loss = g_loss = None
    for step in range(steps):
        z = mx.nd.array(rng.randn(batch, nz, 1, 1).astype("float32"))
        real = real_batch(batch, rng)
        # D step: real -> 1, fake -> 0
        with ag.record():
            fake = gen(z)
            l_d = (bce(dis(real).reshape((-1,)), ones) +
                   bce(dis(fake.detach()).reshape((-1,)), zeros)).mean()
        l_d.backward()
        dt.step(batch)
        # G step: fool D
        with ag.record():
            fake = gen(z)
            l_g = bce(dis(fake).reshape((-1,)), ones).mean()
        l_g.backward()
        gt.step(batch)
        d_loss, g_loss = float(l_d.asnumpy()), float(l_g.asnumpy())
        if step % 20 == 0:
            log("step %3d  d_loss %.4f  g_loss %.4f"
                % (step, d_loss, g_loss))
    return gen, dis, d_loss, g_loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    args = ap.parse_args()
    gen, dis, d_loss, g_loss = train(args.steps)
    rng = np.random.RandomState(1)
    z = mx.nd.array(rng.randn(8, 16, 1, 1).astype("float32"))
    samples = gen(z).asnumpy()
    top = samples[:, :, :8, :].mean()
    bottom = samples[:, :, 8:, :].mean()
    print("final d_loss %.4f g_loss %.4f" % (d_loss, g_loss))
    print("generated structure: top mean %.3f, bottom mean %.3f "
          "(target: dark top, bright bottom)" % (top, bottom))


if __name__ == "__main__":
    main()
