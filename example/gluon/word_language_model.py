"""LSTM word language model — reference
`example/gluon/word_language_model/train.py` equivalent (Gluon LSTM over
bucketed text; synthetic corpus when no data present)."""
import argparse
import logging
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.models import RNNModel


def batchify(data, batch_size):
    nbatch = len(data) // batch_size
    data = np.asarray(data[:nbatch * batch_size]).reshape(
        batch_size, nbatch).T
    return data


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", type=str, default="lstm")
    p.add_argument("--emsize", type=int, default=64)
    p.add_argument("--nhid", type=int, default=64)
    p.add_argument("--nlayers", type=int, default=2)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--clip", type=float, default=0.25)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--bptt", type=int, default=16)
    p.add_argument("--vocab", type=int, default=200)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    # synthetic markov-ish corpus
    rng = np.random.RandomState(0)
    corpus = [0]
    for _ in range(20000):
        corpus.append((corpus[-1] * 31 + rng.randint(0, 7)) % args.vocab)
    data = batchify(corpus, args.batch_size).astype("float32")

    model = RNNModel(args.model, args.vocab, args.emsize, args.nhid,
                     args.nlayers, dropout=0.2)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0,
                             "wd": 0})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total_L = 0.0
        n = 0
        hidden = model.begin_state(batch_size=args.batch_size)
        tic = time.time()
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = mx.nd.array(data[i:i + args.bptt])
            y = mx.nd.array(data[i + 1:i + 1 + args.bptt]).reshape((-1,))
            hidden = [h.detach() for h in hidden]
            with autograd.record():
                output, hidden = model(x, hidden)
                L = loss_fn(output.reshape((-1, args.vocab)), y)
                L = L.mean()
            L.backward()
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(grads, args.clip * args.bptt *
                                         args.batch_size)
            trainer.step(1)
            total_L += float(L.asnumpy())
            n += 1
        ppl = math.exp(total_L / n)
        logging.info("Epoch %d: ppl %.2f (%.1fs)", epoch, ppl,
                     time.time() - tic)
    print("final perplexity: %.2f (vocab %d, random ~%d)"
          % (ppl, args.vocab, args.vocab))


if __name__ == "__main__":
    main()
