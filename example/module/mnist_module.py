"""The full Module workflow: fit, checkpoint, resume, score, predict.

Role parity: reference `example/module/` (mnist_mlp.py / the sequential
module demos): build a symbol, `mod.fit` with an optimizer and metric,
`save_checkpoint` each epoch, `Module.load` + `fit(begin_epoch=...)` to
resume, `score` on a validation iter, `predict` for raw outputs.

Runs on a synthetic MNIST-like problem so it's self-contained; swap the
iterators for `mx.io.MNISTIter` on real data.

Usage:  python mnist_module.py [--epochs 4]
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")


def mlp_symbol(classes=10):
    data = sym.var("data")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=64, name="fc1"),
                       act_type="relu")
    h = sym.Activation(sym.FullyConnected(h, num_hidden=32, name="fc2"),
                       act_type="relu")
    out = sym.SoftmaxOutput(
        sym.FullyConnected(h, num_hidden=classes, name="fc3"),
        sym.var("softmax_label"), name="softmax")
    return out


def make_iters(n=1024, in_dim=32, classes=10, batch=64, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, in_dim).astype("float32") * 2.0
    y = rng.randint(0, classes, n).astype("float32")
    x = centers[y.astype(int)] + rng.randn(n, in_dim).astype("float32")
    split = int(n * 0.8)
    train = mx.io.NDArrayIter(x[:split], y[:split], batch_size=batch,
                              shuffle=True, label_name="softmax_label")
    val = mx.io.NDArrayIter(x[split:], y[split:], batch_size=batch,
                            label_name="softmax_label")
    return train, val


def train(epochs=4, prefix=None, log=print):
    prefix = prefix or os.path.join(tempfile.gettempdir(), "mnist_module")
    train_iter, val_iter = make_iters()

    mod = mx.mod.Module(mlp_symbol(), context=mx.cpu(),
                        data_names=["data"],
                        label_names=["softmax_label"])

    # phase 1: train the first half, checkpointing every epoch
    half = max(1, epochs // 2)
    ckpt = mx.callback.do_checkpoint(prefix)
    mod.fit(train_iter, eval_data=val_iter, eval_metric="acc",
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier(),
            num_epoch=half, epoch_end_callback=ckpt)

    # phase 2: RESUME from the checkpoint into a fresh module
    sym_loaded, arg_params, aux_params = mx.model.load_checkpoint(
        prefix, half)
    mod2 = mx.mod.Module(sym_loaded, context=mx.cpu(),
                         data_names=["data"],
                         label_names=["softmax_label"])
    train_iter.reset()
    mod2.fit(train_iter, eval_data=val_iter, eval_metric="acc",
             optimizer="sgd", optimizer_params={"learning_rate": 0.1},
             arg_params=arg_params, aux_params=aux_params,
             begin_epoch=half, num_epoch=epochs)

    # score + predict on the validation set
    val_iter.reset()
    score = mod2.score(val_iter, "acc")
    acc = dict(score)["accuracy"]
    val_iter.reset()
    preds = mod2.predict(val_iter)
    log("val accuracy %.3f, predictions %s" % (acc, preds.shape))
    return acc, preds


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args()
    train(epochs=args.epochs)
