"""Advantage actor-critic (reference
`example/reinforcement-learning/a3c/a3c.py` and
`parallel_actor_critic/train.py` — policy + value heads on a shared
trunk, advantage-weighted policy gradient with entropy bonus).

Single-process port on a stochastic corridor environment. Exercises:
two-headed network, REINFORCE-style loss where the gradient signal is a
detached advantage (no dataset labels), entropy regularization.

    python example/reinforcement-learning/actor_critic.py
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

N_STATES = 10   # corridor positions; reward at the right end
N_ACTIONS = 2   # left / right


class ACNet(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.trunk = nn.Dense(64, activation="relu", in_units=N_STATES)
            self.policy = nn.Dense(N_ACTIONS, in_units=64)
            self.value = nn.Dense(1, in_units=64)

    def hybrid_forward(self, F, x):
        h = self.trunk(x)
        return self.policy(h), self.value(h)


def env_step(state, action, rng):
    # 10% chance the move slips; +5 at the right end, -0.1 per step
    if rng.random() < 0.1:
        action = 1 - action
    state = max(0, min(N_STATES - 1, state + (1 if action == 1 else -1)))
    done = state == N_STATES - 1
    return state, (5.0 if done else -0.1), done


def one_hot(s):
    v = np.zeros((1, N_STATES), np.float32)
    v[0, s] = 1.0
    return v


def train(episodes=300, gamma=0.97, lr=1e-2, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = ACNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    returns = []
    for ep in range(episodes):
        s, done, steps = 0, False, 0
        states, actions, rewards = [], [], []
        while not done and steps < 50:
            logits, _ = net(nd.array(one_hot(s)))
            p = np.exp(logits.asnumpy()[0] - logits.asnumpy()[0].max())
            p = p / p.sum()
            a = int(rng.choice(N_ACTIONS, p=p))
            s2, r, done = env_step(s, a, rng)
            states.append(one_hot(s)[0])
            actions.append(a)
            rewards.append(r)
            s = s2
            steps += 1
        # n-step discounted returns
        G, rets = 0.0, []
        for r in reversed(rewards):
            G = r + gamma * G
            rets.append(G)
        rets = np.array(rets[::-1], np.float32)
        X = nd.array(np.array(states, np.float32))
        A = nd.array(np.array(actions, np.float32))
        R = nd.array(rets)
        with ag.record():
            logits, values = net(X)
            logp = nd.log_softmax(logits, axis=-1)
            taken = nd.pick(logp, A, axis=1)
            adv = R - values.reshape((-1,))
            adv_detached = adv.detach()               # stop-gradient
            policy_loss = -(taken * adv_detached).mean()
            value_loss = (adv ** 2).mean()
            entropy = -(nd.softmax(logits, axis=-1) * logp).sum(axis=1).mean()
            loss = policy_loss + 0.5 * value_loss - 0.01 * entropy
        loss.backward()
        trainer.step(1)
        returns.append(sum(rewards))
        if ep % 50 == 0:
            log("episode %3d  return %6.2f  len %d"
                % (ep, returns[-1], steps))
    return returns


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=300)
    train(episodes=ap.parse_args().episodes)
