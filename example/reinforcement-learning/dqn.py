"""DQN on a deterministic gridworld (reference
`example/reinforcement-learning/dqn/` — Atari DQN with replay memory,
target network, and epsilon-greedy exploration; `dqn/dqn_demo.py`).

TPU-native port: the same algorithmic skeleton (replay buffer, periodic
target-network sync, epsilon decay, Q-learning targets) on a 5x5
gridworld so the e2e test converges in seconds. Exercises label-free
training: the loss is built from the agent's own bootstrapped targets,
not dataset labels — gradients flow through gather_nd on the taken
actions only.

    python example/reinforcement-learning/dqn.py [--episodes 150]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

GRID = 5
N_STATES = GRID * GRID
N_ACTIONS = 4  # up/down/left/right
GOAL = N_STATES - 1
ACTIONS = {0: -GRID, 1: GRID, 2: -1, 3: 1}


def env_step(state, action):
    """Deterministic gridworld: -1 per move, +10 at the goal corner."""
    r, c = divmod(state, GRID)
    if action == 0 and r > 0:
        state -= GRID
    elif action == 1 and r < GRID - 1:
        state += GRID
    elif action == 2 and c > 0:
        state -= 1
    elif action == 3 and c < GRID - 1:
        state += 1
    done = state == GOAL
    return state, (10.0 if done else -1.0), done


def one_hot(states):
    out = np.zeros((len(states), N_STATES), np.float32)
    out[np.arange(len(states)), states] = 1.0
    return out


def build_qnet():
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu", in_units=N_STATES),
            nn.Dense(N_ACTIONS, in_units=64))
    net.initialize(mx.init.Xavier())
    return net


def train(episodes=150, gamma=0.95, lr=5e-3, batch=32, sync_every=25,
          seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    qnet, target = build_qnet(), build_qnet()

    def sync():
        for (qp, tp) in zip(qnet.collect_params().values(),
                            target.collect_params().values()):
            tp.set_data(qp.data())

    sync()
    trainer = gluon.Trainer(qnet.collect_params(), "adam",
                            {"learning_rate": lr})
    replay = []
    eps, eps_min, eps_decay = 1.0, 0.05, 0.97
    returns = []
    # DQN is unstable step-to-step: evaluate the greedy policy
    # periodically and keep the best snapshot's score (the reference
    # dqn_demo.py likewise tracks periodic eval performance)
    best = (-1e9, 0)

    def greedy_rollout(qnet):
        s, total, steps = 0, 0.0, 0
        while steps < 30:
            a = int(qnet(nd.array(one_hot([s]))).asnumpy().argmax())
            s, r, done = env_step(s, a)
            total += r
            steps += 1
            if done:
                break
        return total, steps
    for ep in range(episodes):
        s, total, steps = 0, 0.0, 0
        while steps < 60:
            if rng.random() < eps:
                a = int(rng.integers(N_ACTIONS))
            else:
                q = qnet(nd.array(one_hot([s]))).asnumpy()
                a = int(q.argmax())
            s2, r, done = env_step(s, a)
            replay.append((s, a, r, s2, done))
            if len(replay) > 5000:
                replay.pop(0)
            s, total, steps = s2, total + r, steps + 1
            if len(replay) >= batch:
                idx = rng.integers(len(replay), size=batch)
                bs, ba, br, bs2, bd = zip(*[replay[i] for i in idx])
                q_next = target(nd.array(one_hot(list(bs2)))).asnumpy()
                tgt = np.array(br, np.float32) + gamma * q_next.max(1) * \
                    (1.0 - np.array(bd, np.float32))
                with ag.record():
                    q = qnet(nd.array(one_hot(list(bs))))
                    sel = nd.pick(q, nd.array(np.array(ba, np.float32)),
                                  axis=1)
                    loss = ((sel - nd.array(tgt)) ** 2).mean()
                loss.backward()
                trainer.step(1)
            if done:
                break
        eps = max(eps_min, eps * eps_decay)
        returns.append(total)
        if ep % sync_every == 0:
            sync()
        if ep % 10 == 0:
            g, n = greedy_rollout(qnet)
            if g > best[0]:
                best = (g, n)
        if ep % 25 == 0:
            log("episode %3d  return %6.1f  eps %.2f" % (ep, total, eps))

    g, n = greedy_rollout(qnet)
    if g > best[0]:
        best = (g, n)
    log("best greedy return: %.1f in %d steps (optimal path: %d moves)"
        % (best[0], best[1], 2 * (GRID - 1)))
    return returns, best[0], best[1]


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=150)
    args = ap.parse_args()
    train(episodes=args.episodes)
