"""Bidirectional-LSTM sequence sorting (reference
`example/bi-lstm-sort/bi-lstm-sort.ipynb` — train a BiLSTM to output the
sorted version of its input token sequence; each output position needs
GLOBAL context, which is exactly what the forward+backward pass pair
provides).

    python example/bi-lstm-sort/sort.py [--epochs 15]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn, rnn

VOCAB = 20      # token values 0..19
SEQ = 6
EMBED, HIDDEN = 16, 48


class BiLSTMSorter(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, EMBED)
            self.lstm = rnn.LSTM(HIDDEN, bidirectional=True, layout="NTC",
                                 input_size=EMBED)
            self.out = nn.Dense(VOCAB, flatten=False, in_units=2 * HIDDEN)

    def hybrid_forward(self, F, tokens):
        return self.out(self.lstm(self.embed(tokens)))


def make_data(n, rng):
    X = rng.integers(0, VOCAB, (n, SEQ))
    Y = np.sort(X, axis=1)
    return X.astype(np.float32), Y.astype(np.float32)


def train(epochs=15, batch=64, lr=5e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = BiLSTMSorter()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    X, Y = make_data(1024, rng)
    Xv, Yv = make_data(256, rng)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            with ag.record():
                out = net(nd.array(X[i:i + batch]))      # (B, T, V)
                loss = loss_fn(out, nd.array(Y[i:i + batch])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        pred = net(nd.array(Xv)).asnumpy().argmax(-1)
        tok_acc = float((pred == Yv).mean())
        seq_acc = float((pred == Yv).all(axis=1).mean())
        log("epoch %2d  loss %.4f  token acc %.3f  full-seq acc %.3f"
            % (ep, tot / (len(X) // batch), tok_acc, seq_acc))
    return tok_acc, seq_acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    train(epochs=ap.parse_args().epochs)
