"""FCN semantic segmentation (reference `example/fcn-xs/` — VGG-FCN with
`symbol_fcnxs.py` score layers, Deconvolution bilinear upsampling and
Crop to input size, per-pixel softmax).

Port: conv encoder (stride 4 total) -> 1x1 score conv -> Deconvolution
x4 upsample initialized bilinear (reference `init_fcnxs.py:29`) -> Crop
to the input -> per-pixel softmax CE, on a synthetic shapes dataset.
Exercises Deconvolution, Crop, bilinear kernel init, and NCHW per-pixel
losses end-to-end.

    python example/fcn-xs/fcn.py [--epochs 8]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

SIZE = 32
N_CLASSES = 3  # background / square / disk


def bilinear_kernel(channels, k):
    """reference init_fcnxs.py:29 bilinear filler."""
    factor = (k + 1) // 2
    center = factor - 1.0 if k % 2 == 1 else factor - 0.5
    og = np.ogrid[:k, :k]
    filt = (1 - abs(og[0] - center) / factor) * \
        (1 - abs(og[1] - center) / factor)
    w = np.zeros((channels, channels, k, k), np.float32)
    for c in range(channels):
        w[c, c] = filt
    return w


class FCN(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = nn.Conv2D(16, 3, padding=1, activation="relu",
                                in_channels=3)
            self.p1 = nn.MaxPool2D(2, 2)
            self.c2 = nn.Conv2D(32, 3, padding=1, activation="relu",
                                in_channels=16)
            self.p2 = nn.MaxPool2D(2, 2)
            self.score = nn.Conv2D(N_CLASSES, 1, in_channels=32)
            # fixed bilinear upsampling kernel (reference init_fcnxs.py:29
            # initializes the deconv filter bilinear; grad_req null keeps
            # it frozen like the reference's fixed filler)
            self.up_weight = self.params.get(
                "up_weight", shape=(N_CLASSES, N_CLASSES, 8, 8),
                init=mx.init.Constant(bilinear_kernel(N_CLASSES, 8)),
                grad_req="null")

    def hybrid_forward(self, F, x, up_weight=None):
        h = self.p2(self.c2(self.p1(self.c1(x))))
        s = self.score(h)                       # (B, C, S/4, S/4)
        up = F.Deconvolution(s, up_weight, kernel=(8, 8),
                             stride=(4, 4), pad=(2, 2),
                             num_filter=N_CLASSES, no_bias=True)
        return F.Crop(up, x, offset=(0, 0))     # crop to input HxW


def make_data(n, rng):
    imgs = np.zeros((n, 3, SIZE, SIZE), np.float32)
    labels = np.zeros((n, SIZE, SIZE), np.float32)
    for i in range(n):
        img = rng.normal(0, 0.1, (3, SIZE, SIZE)).astype(np.float32)
        # a square of class 1
        x0, y0 = rng.integers(2, SIZE - 12, 2)
        img[0, y0:y0 + 10, x0:x0 + 10] += 1.0
        labels[i, y0:y0 + 10, x0:x0 + 10] = 1
        # a disk of class 2
        cx, cy = rng.integers(8, SIZE - 8, 2)
        yy, xx = np.ogrid[:SIZE, :SIZE]
        disk = (yy - cy) ** 2 + (xx - cx) ** 2 <= 25
        img[1][disk] += 1.0
        labels[i][disk] = 2
        imgs[i] = img
    return imgs, labels


def train(epochs=8, batch=8, lr=0.05, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = FCN()
    net.initialize(mx.init.Xavier())
    X, Y = make_data(64, rng)
    Xv, Yv = make_data(16, rng)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            xb = nd.array(X[i:i + batch])
            yb = nd.array(Y[i:i + batch])
            with ag.record():
                out = net(xb)
                loss = loss_fn(out, yb).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        pred = net(nd.array(Xv)).asnumpy().argmax(1)
        acc = float((pred == Yv).mean())
        log("epoch %d  loss %.4f  pixel-acc %.3f"
            % (ep, tot / (len(X) // batch), acc))
    return acc, pred


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    train(epochs=ap.parse_args().epochs)
