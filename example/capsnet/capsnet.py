"""Capsule network with dynamic routing (reference
`example/capsnet/capsulenet.py` + `capsulelayers.py` — primary caps,
digit caps with routing-by-agreement, squash nonlinearity, margin loss).

Port: the same three stages on small synthetic digits; the routing loop
is a fixed-iteration agreement update (softmax coupling -> weighted vote
-> squash -> agreement dot), fully traced into one XLA program.

    python example/capsnet/capsnet.py [--epochs 6]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

SIZE = 16
N_CLASS = 4
PRIM_CAPS, PRIM_DIM = 8, 8
DIGIT_DIM = 16
ROUTING_ITERS = 3


def squash(s, axis=-1):
    """reference capsulelayers.py:squash."""
    sq = (s ** 2).sum(axis=axis, keepdims=True)
    return sq / (1.0 + sq) * s / nd.sqrt(sq + 1e-9)


class CapsNet(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.conv = nn.Conv2D(32, 5, strides=2, padding=2,
                                  activation="relu", in_channels=1)
            self.primary = nn.Conv2D(PRIM_CAPS * PRIM_DIM, 5, strides=2,
                                     padding=2, in_channels=32)
            n_prim = PRIM_CAPS * (SIZE // 4) * (SIZE // 4)
            self.routing_weight = self.params.get(
                "routing_weight",
                shape=(1, n_prim, N_CLASS, DIGIT_DIM, PRIM_DIM),
                init=mx.init.Normal(0.05))

    def hybrid_forward(self, F, x, routing_weight=None):
        B = x.shape[0]
        h = self.primary(self.conv(x))           # (B, C*D, S/4, S/4)
        u = h.reshape((B, PRIM_CAPS, PRIM_DIM, -1))
        u = u.transpose((0, 1, 3, 2)).reshape((B, -1, PRIM_DIM))
        u = squash(u)                            # (B, P, prim_dim)
        # predictions u_hat[b, i, j, :] = W_ij @ u_i
        uh = (routing_weight *
              u.reshape((B, -1, 1, 1, PRIM_DIM))).sum(axis=-1)
        # (B, P, N_CLASS, DIGIT_DIM)
        b_logits = nd.zeros((B, uh.shape[1], N_CLASS, 1))
        for _ in range(ROUTING_ITERS):
            c = nd.softmax(b_logits, axis=2)     # coupling over classes
            s = (c * uh).sum(axis=1)             # (B, N_CLASS, DIGIT_DIM)
            v = squash(s, axis=-1)
            agree = (uh * v.reshape((B, 1, N_CLASS, DIGIT_DIM))
                     ).sum(axis=-1, keepdims=True)
            b_logits = b_logits + agree
        return nd.sqrt((v ** 2).sum(axis=-1) + 1e-9)   # class lengths


def margin_loss(lengths, onehot):
    """reference capsulenet.py margin loss."""
    pos = nd.maximum(0.0, 0.9 - lengths) ** 2
    neg = nd.maximum(0.0, lengths - 0.1) ** 2
    return (onehot * pos + 0.5 * (1 - onehot) * neg).sum(axis=1).mean()


def make_digits(n, rng):
    X = rng.normal(0, 0.15, (n, 1, SIZE, SIZE)).astype(np.float32)
    y = rng.integers(0, N_CLASS, n)
    for i in range(n):
        if y[i] == 0:     # horizontal bar
            X[i, 0, 7:9, 2:14] += 1.5
        elif y[i] == 1:   # vertical bar
            X[i, 0, 2:14, 7:9] += 1.5
        elif y[i] == 2:   # diagonal
            for d in range(12):
                X[i, 0, 2 + d, 2 + d] += 1.5
        else:             # box outline
            X[i, 0, 3:13, 3] += 1.5
            X[i, 0, 3:13, 12] += 1.5
            X[i, 0, 3, 3:13] += 1.5
            X[i, 0, 12, 3:13] += 1.5
    return X, y.astype(np.int64)


def train(epochs=6, batch=32, lr=2e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = CapsNet()
    net.initialize(mx.init.Xavier())
    X, Y = make_digits(256, rng)
    Xv, Yv = make_digits(96, rng)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            yb = Y[i:i + batch]
            onehot = np.zeros((len(yb), N_CLASS), np.float32)
            onehot[np.arange(len(yb)), yb] = 1.0
            with ag.record():
                lengths = net(nd.array(X[i:i + batch]))
                loss = margin_loss(lengths, nd.array(onehot))
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        pred = net(nd.array(Xv)).asnumpy().argmax(1)
        acc = float((pred == Yv).mean())
        log("epoch %d  margin loss %.4f  acc %.3f"
            % (ep, tot / (len(X) // batch), acc))
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    train(epochs=ap.parse_args().epochs)
