"""Stochastic depth (reference `example/stochastic-depth/sd_module.py` /
`sd_cifar10.py` — residual blocks randomly skipped at train time with
depth-linear survival probabilities; at inference every block runs,
scaled by its survival probability).

Exercises train/inference mode divergence driven by framework RNG: the
per-block Bernoulli gate uses mx.nd.Dropout's counter-hash stream, and
eval is deterministic.

    python example/stochastic-depth/stochastic_depth.py [--epochs 8]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, gluon, nd
from mxnet_tpu.gluon import nn

SIZE = 16
N_CLASS = 4


class SDBlock(gluon.HybridBlock):
    """Residual block skipped with prob 1-p_survive during training
    (reference sd_module.py death_rate)."""

    def __init__(self, channels, p_survive, **kw):
        super().__init__(**kw)
        self._p = p_survive
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="body_")
            self.body.add(
                nn.Conv2D(channels, 3, padding=1, in_channels=channels),
                nn.BatchNorm(),
                nn.Activation("relu"),
                nn.Conv2D(channels, 3, padding=1, in_channels=channels),
                nn.BatchNorm())

    def hybrid_forward(self, F, x):
        out = self.body(x)
        if ag.is_training():
            # one Bernoulli per forward: Dropout on a scalar-ish gate
            # (keep-prob p) zeroes or keeps the whole branch; Dropout's
            # 1/p rescale is undone so the kept branch passes unscaled,
            # matching the reference train-time semantics
            gate = F.Dropout(F.ones((1, 1, 1, 1)), p=1.0 - self._p) \
                * self._p
            return F.Activation(x + out * gate, act_type="relu")
        return F.Activation(x + self._p * out, act_type="relu")


class SDNet(gluon.HybridBlock):
    def __init__(self, n_blocks=6, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.stem = nn.Conv2D(16, 3, padding=1, activation="relu",
                                  in_channels=1)
            self.blocks = nn.HybridSequential(prefix="blocks_")
            for i in range(n_blocks):
                # depth-linear survival: p_l = 1 - l/L * (1 - p_L)
                p = 1.0 - (i + 1) / n_blocks * 0.5
                self.blocks.add(SDBlock(16, p))
            self.head = nn.HybridSequential(prefix="head_")
            self.head.add(nn.GlobalAvgPool2D(), nn.Flatten(),
                          nn.Dense(N_CLASS, in_units=16))

    def hybrid_forward(self, F, x):
        return self.head(self.blocks(self.stem(x)))


def make_data(n, rng):
    X = rng.normal(0, 0.2, (n, 1, SIZE, SIZE)).astype(np.float32)
    y = rng.integers(0, N_CLASS, n)
    for i in range(n):
        q = y[i]
        r0, c0 = (q // 2) * 8, (q % 2) * 8
        X[i, 0, r0:r0 + 8, c0:c0 + 8] += 1.0
    return X, y.astype(np.float32)


def train(epochs=8, batch=32, lr=2e-3, seed=0, log=print):
    rng = np.random.default_rng(seed)
    mx.random.seed(seed)
    net = SDNet()
    net.initialize(mx.init.Xavier())
    X, Y = make_data(256, rng)
    Xv, Yv = make_data(96, rng)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        tot = 0.0
        for i in range(0, len(X), batch):
            with ag.record():
                out = net(nd.array(X[i:i + batch]))
                loss = loss_fn(out, nd.array(Y[i:i + batch])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        pred = net(nd.array(Xv)).asnumpy().argmax(1)
        acc = float((pred == Yv.astype(np.int64)).mean())
        log("epoch %d  loss %.4f  acc %.3f"
            % (ep, tot / (len(X) // batch), acc))
    # eval determinism: two eval passes must agree exactly
    o1 = net(nd.array(Xv)).asnumpy()
    o2 = net(nd.array(Xv)).asnumpy()
    deterministic = bool(np.array_equal(o1, o2))
    return acc, deterministic


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    train(epochs=ap.parse_args().epochs)
