"""SVRG optimization (reference `example/svrg_module/` +
`python/mxnet/contrib/svrg_optimization/svrg_module.py` — maintain a
full-gradient snapshot at w_tilde each epoch; each step uses
g_i(w) - g_i(w_tilde) + mu for variance-reduced updates).

Port on a convex least-squares problem where variance reduction provably
helps: the e2e test asserts SVRG reaches a lower loss than plain SGD
under the SAME learning rate and step budget.

    python example/svrg_module/svrg.py [--epochs 12]
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag, nd


def make_problem(seed=0, n=256, d=20):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    # ill-conditioned: scale features
    X *= np.geomspace(1.0, 6.0, d).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32)
    y = X @ w_true + 0.05 * rng.standard_normal(n).astype(np.float32)
    return X, y, w_true


def batch_grad(w, Xb, yb):
    with ag.record():
        loss = ((nd.dot(nd.array(Xb), w) - nd.array(yb)) ** 2).mean()
    loss.backward()
    return w.grad.asnumpy().copy(), float(loss.asnumpy())


def full_loss(w_np, X, y):
    return float(((X @ w_np - y) ** 2).mean())


def run_sgd(X, y, epochs, batch, lr, seed):
    rng = np.random.default_rng(seed)
    w = nd.zeros((X.shape[1],))
    w.attach_grad()
    for _ in range(epochs):
        order = rng.permutation(len(X))
        for i in range(0, len(X), batch):
            idx = order[i:i + batch]
            g, _ = batch_grad(w, X[idx], y[idx])
            w[:] = nd.array(w.asnumpy() - lr * g)
            w.attach_grad()
    return full_loss(w.asnumpy(), X, y)


def run_svrg(X, y, epochs, batch, lr, seed, snapshot_every=8):
    rng = np.random.default_rng(seed)
    w = nd.zeros((X.shape[1],))
    w.attach_grad()
    since_snap = snapshot_every   # force a snapshot on the first step
    w_tilde = mu = None
    for _ in range(epochs):
        order = rng.permutation(len(X))
        for i in range(0, len(X), batch):
            if since_snap >= snapshot_every:
                # full-gradient snapshot at w_tilde (reference svrg_module
                # update_full_grads); SVRG's correction variance grows
                # with ||w - w_tilde||, so the snapshot interval m must
                # keep m*lr*L bounded — snapshot every few steps
                w_tilde = w.asnumpy().copy()
                wt = nd.array(w_tilde)
                wt.attach_grad()
                mu, _ = batch_grad(wt, X, y)
                since_snap = 0
            idx = order[i:i + batch]
            g_w, _ = batch_grad(w, X[idx], y[idx])
            wt = nd.array(w_tilde)
            wt.attach_grad()
            g_t, _ = batch_grad(wt, X[idx], y[idx])
            vr = g_w - g_t + mu       # variance-reduced direction
            w[:] = nd.array(w.asnumpy() - lr * vr)
            w.attach_grad()
            since_snap += 1
    return full_loss(w.asnumpy(), X, y)


def train(epochs=10, batch=8, lr=1e-2, seed=0, log=print):
    X, y, _ = make_problem(seed)
    sgd_loss = run_sgd(X, y, epochs, batch, lr, seed + 1)
    svrg_loss = run_svrg(X, y, epochs, batch, lr, seed + 1)
    log("final loss  sgd=%.5f  svrg=%.5f  (svrg/sgd=%.3f)"
        % (sgd_loss, svrg_loss, svrg_loss / max(sgd_loss, 1e-12)))
    return sgd_loss, svrg_loss


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    train(epochs=ap.parse_args().epochs)
