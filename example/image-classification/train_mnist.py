"""Train MLP/LeNet on MNIST — CLI parity with the reference
`example/image-classification/train_mnist.py` (Module.fit path, SURVEY §3.4).

Runs on synthetic MNIST when the real idx files are absent (no egress).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.io import NDArrayIter


def get_mnist(flat):
    from mxnet_tpu.gluon.data.vision import MNIST
    train = MNIST(train=True)
    val = MNIST(train=False)

    def to_arrays(ds):
        X = ds._data.asnumpy().astype("float32") / 255.0
        X = X.reshape(len(ds), -1) if flat else \
            X.transpose(0, 3, 1, 2)
        return X, np.asarray(ds._label, dtype="float32")

    return to_arrays(train), to_arrays(val)


def mlp_symbol():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu", name="relu2")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                                name="softmax")


def lenet_symbol():
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    a1 = mx.sym.Activation(c1, act_type="tanh", name="tanh1")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2),
                        name="pool1")
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    a2 = mx.sym.Activation(c2, act_type="tanh", name="tanh2")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2),
                        name="pool2")
    f = mx.sym.Flatten(p2, name="flatten")
    f1 = mx.sym.FullyConnected(f, num_hidden=500, name="fc1")
    a3 = mx.sym.Activation(f1, act_type="tanh", name="tanh3")
    f2 = mx.sym.FullyConnected(a3, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(f2, mx.sym.var("softmax_label"),
                                name="softmax")


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.add_argument("--network", type=str, default="mlp",
                        choices=["mlp", "lenet"])
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--optimizer", type=str, default="sgd")
    parser.add_argument("--kv-store", type=str, default="local")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)
    flat = args.network == "mlp"
    (Xtr, Ytr), (Xva, Yva) = get_mnist(flat)
    train = NDArrayIter(Xtr, Ytr, args.batch_size, shuffle=True)
    val = NDArrayIter(Xva, Yva, args.batch_size)

    sym = mlp_symbol() if flat else lenet_symbol()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(train, eval_data=val,
            kvstore=args.kv_store,
            optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50),
            num_epoch=args.num_epochs)
    score = mod.score(val, "acc")
    print("final validation accuracy: %.4f" % score[0][1])


if __name__ == "__main__":
    main()
