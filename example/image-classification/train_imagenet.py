"""ImageNet-style training — CLI parity with the reference
`example/image-classification/train_imagenet.py` (`--kv-store
dist_tpu_sync` is the BASELINE.json north-star config).

TPU-native path: `--kv-store dist_tpu_sync` (or any multi-device run) uses
mxnet_tpu.parallel.ShardedTrainer — one compiled SPMD step with in-graph
allreduce over the ICI mesh (no PS processes; SURVEY §5.8). Data comes from
a .rec file (native C++ pipeline) or synthetic tensors.
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import vision


def parse_args():
    p = argparse.ArgumentParser(description="train imagenet (TPU)")
    p.add_argument("--network", type=str, default="resnet50_v1")
    p.add_argument("--batch-size", type=int, default=256,
                   help="global batch size")
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--kv-store", type=str, default="dist_tpu_sync")
    p.add_argument("--dtype", type=str, default="bfloat16")
    p.add_argument("--image-shape", type=str, default="3,224,224")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--data-train", type=str, default=None,
                   help=".rec file (JPEG ImageRecordIO or raw container); "
                        "synthetic if absent")
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter edge before crop")
    p.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939")
    p.add_argument("--rgb-std", type=str, default="58.393,57.12,57.375")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel size")
    p.add_argument("--log-interval", type=int, default=10)
    return p.parse_args()


def main():
    args = parse_args()
    logging.basicConfig(level=logging.INFO)
    import jax
    n_dev = len(jax.devices())
    logging.info("devices: %d (%s), kv-store: %s", n_dev,
                 jax.devices()[0].platform, args.kv_store)

    shape = tuple(int(x) for x in args.image_shape.split(","))
    net = vision.get_model(args.network, classes=args.num_classes)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1,) + shape))  # resolve deferred shapes
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    mesh = parallel.make_mesh(dp=n_dev // args.tp, tp=args.tp)
    bs = args.batch_size
    use_rec = args.data_train and os.path.exists(args.data_train)

    preprocess = None
    if use_rec:
        # data-fed path: host ships raw uint8 NHWC; normalize + layout +
        # bf16 cast run INSIDE the compiled step (TPU-native input pipeline)
        import jax.numpy as jnp
        mean = jnp.array([float(v) for v in args.rgb_mean.split(",")],
                         jnp.float32)
        std = jnp.array([float(v) for v in args.rgb_std.split(",")],
                        jnp.float32)
        cdt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

        def preprocess(x):  # (N,H,W,C) u8 → (N,C,H,W) model dtype
            x = (x.astype(jnp.float32) - mean) / std
            return x.transpose(0, 3, 1, 2).astype(cdt)

    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9, "wd": 1e-4}, mesh=mesh,
        preprocess=preprocess)

    if use_rec:
        try:
            from mxnet_tpu import _native
            pump = _native.Pump(args.data_train, bs, shape,
                                resize=args.resize, rand_crop=True,
                                rand_mirror=True, shuffle=True,
                                u8_output=True, depth=4)
        except Exception as e:
            # no native lib on this host — pure-Python decode fallback
            # (ImageRecordIter PIL path); slower but the same training
            logging.warning("native pump unavailable (%s); falling back "
                            "to the Python ImageRecordIter", e)
            pump = None
        if pump is not None:
            logging.info("native pump: %d batches/epoch",
                         pump.batches_per_epoch)

            def batches():
                pump.reset()
                while True:
                    item = pump.next()
                    if item is None:
                        return
                    yield item
        else:
            from mxnet_tpu.io import ImageRecordIter
            it = ImageRecordIter(path_imgrec=args.data_train,
                                 data_shape=shape, batch_size=bs,
                                 shuffle=True, resize=args.resize,
                                 rand_crop=True, rand_mirror=True)

            def batches():
                it.reset()
                while True:
                    try:
                        b = it.next()
                    except StopIteration:
                        return
                    # python path emits normalized f32 NCHW; undo the u8
                    # preprocess contract by feeding NHWC u8-range data
                    x = b.data[0].asnumpy().transpose(0, 2, 3, 1)
                    yield x.astype(np.uint8), b.label[0].asnumpy()
    else:
        logging.info("using synthetic data")
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.rand(bs, *shape), dtype="float32").astype(
            args.dtype)
        y = mx.nd.array(rng.randint(0, args.num_classes, bs).astype(
            "float32"))

        def batches():
            for _ in range(args.steps_per_epoch):
                yield x, y

    for epoch in range(args.num_epochs):
        tic = time.time()
        n_img = 0
        last = tic
        for i, (xb, yb) in enumerate(batches()):
            loss = trainer.step(xb, yb)
            n_img += bs
            if (i + 1) % args.log_interval == 0:
                loss.wait_to_read()
                now = time.time()
                speed = args.log_interval * bs / (now - last)
                last = now
                logging.info("Epoch[%d] Batch [%d] Speed: %.2f samples/sec "
                             "loss=%.4f", epoch, i + 1, speed,
                             float(loss.asnumpy()))
        dt = time.time() - tic
        logging.info("Epoch[%d] time %.1fs throughput %.1f img/s",
                     epoch, dt, n_img / dt)
    trainer.sync_back()


if __name__ == "__main__":
    main()
