"""Data-fed ResNet-50 training benchmark: the native IO pipeline
(lib/libmxtpu.so: RecordIO scan -> JPEG/raw decode -> augment -> uint8
batches, double-buffered) feeding the compiled training step on the chip.

This is the apples-to-apples counterpart of the reference's headline
298.51 img/s (V100, train_imagenet.py through its C++ ImageRecordIter,
reference docs perf.md:252) — unlike bench.py, whose batches are
generated in-graph.

Pipeline design (TPU-native):
- host ships raw uint8 NHWC (4x fewer bytes over the host->device link
  than f32); normalize + layout + bf16 cast run INSIDE the compiled step
  (ShardedTrainer preprocess), fused by XLA;
- batches transfer as individual ~4.8MB puts (the tunneled link collapses
  on large buffers), stacked on device and dispatched as one step_many
  chunk; a feeder thread stages chunk N+1 while the device runs chunk N.

The benchmark decomposes throughput into its four independent rates:
  io       host decode+augment rate (pump drain, no device)
  wire     host->device transfer rate, idle link
  wire_c   host->device transfer rate WHILE compute is in flight (on the
           tunneled chip transfers contend with compute RPCs; on a real
           PCIe-attached host wire_c ~= wire)
  compute  the same training program with batches generated in-graph
and reports fed-rate plus pipeline efficiency = fed / min(io, wire_c,
compute) — how close the overlap gets to the binding constraint.

Env knobs: DF_BATCH (32), DF_CHUNK (steps per dispatch, 16), DF_CHUNKS
(measured chunks, 6), DF_N_IMG (records in the generated .rec, 1024),
DF_FORMAT (raw|jpg; jpg decode is host-core-bound: ~430 img/s/core
measured — this box has 1 core, a real TPU-VM host has 100+).
"""
import io as pyio
import json
import os
import queue
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

BASELINE_IMG_S = 298.51  # reference perf.md:252 (V100, fp32, batch 32)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(metric, value, unit, **kw):
    print(json.dumps(dict(metric=metric, value=round(value, 2), unit=unit,
                          **kw)), flush=True)


def make_rec(path, n, size, fmt):
    from mxnet_tpu.recordio import MXRecordIO, IRHeader, pack, pack_img
    rng = np.random.RandomState(0)
    rec = MXRecordIO(path, "w")
    # a handful of distinct images referenced round-robin keeps .rec build
    # time negligible while still exercising full decode per record
    base = [(rng.rand(size, size, 3) * 255).astype(np.uint8)
            for _ in range(32)]
    if fmt == "jpg":
        from PIL import Image
        payloads = []
        for im in base:
            b = pyio.BytesIO()
            Image.fromarray(im).save(b, format="JPEG", quality=90)
            payloads.append(b.getvalue())
        for i in range(n):
            rec.write(pack(IRHeader(0, float(i % 1000), i, 0),
                           payloads[i % 32]))
    else:
        for i in range(n):
            rec.write(pack_img(IRHeader(0, float(i % 1000), i, 0),
                               base[i % 32], img_fmt=".raw"))
    rec.close()


def main():
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel, _native
    from mxnet_tpu.gluon.model_zoo import vision

    batch = int(os.environ.get("DF_BATCH", "32"))
    chunk = int(os.environ.get("DF_CHUNK", "16"))
    n_chunks = int(os.environ.get("DF_CHUNKS", "6"))
    n_img = int(os.environ.get("DF_N_IMG", "1024"))
    fmt = os.environ.get("DF_FORMAT", "raw")
    image = 224
    src_size = 256

    rec_path = "/tmp/bench_datafed_%s_%d.rec" % (fmt, n_img)
    if not os.path.exists(rec_path):
        log("building %s (%d records of %d^2 %s)..."
            % (rec_path, n_img, src_size, fmt))
        make_rec(rec_path, n_img, src_size, fmt)

    log("devices:", jax.devices())
    d = jax.devices()[0]
    shape = (3, image, image)

    # --- phase 1: pure IO (pump drain, no device) ---
    pump = _native.Pump(rec_path, batch, shape, rand_crop=True,
                        rand_mirror=True, shuffle=True, u8_output=True,
                        depth=4)
    drain_n = min(pump.batches_per_epoch, 40)
    for _ in range(4):
        pump.next()  # warm
    t0 = time.time()
    got = 0
    while got < drain_n:
        if pump.next() is not None:
            got += 1
    io_rate = drain_n * batch / (time.time() - t0)
    log("pure IO (decode+augment, %s): %.0f img/s" % (fmt, io_rate))
    emit("io_pump_%s_img_per_sec" % fmt, io_rate, "img/s")

    def drain():
        while True:
            item = pump.next()
            if item is not None:
                return item

    # --- phase 2: wire, idle link ---
    xs_host = [drain() for _ in range(16)]
    jax.block_until_ready(jax.device_put(xs_host[0][0], d))
    t0 = time.time()
    for x, _ in xs_host:
        jax.block_until_ready(jax.device_put(x, d))
    wire_rate = 16 * batch / (time.time() - t0)
    log("wire (uint8 b%d puts, idle): %.0f img/s" % (batch, wire_rate))
    emit("wire_idle_img_per_sec", wire_rate, "img/s")

    # --- model + trainer with in-step preprocess ---
    mean = jnp.array([123.68, 116.779, 103.939], jnp.float32)
    std = jnp.array([58.393, 57.12, 57.375], jnp.float32)

    def preprocess(x):
        x = (x.astype(jnp.float32) - mean) / std
        return x.transpose(0, 3, 1, 2).astype(jnp.bfloat16)

    mx.random.seed(0)
    net = vision.resnet50_v1()
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1,) + shape))
    net.cast("bfloat16")
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9},
        mesh=parallel.make_mesh(dp=1), preprocess=preprocess)

    # --- phase 3: pure compute (same program, in-graph uint8 batches) ---
    steps = chunk * n_chunks
    log("compiling bench_span (%d steps)..." % steps)
    l = trainer.bench_span(steps, (batch, image, image, 3), 1000,
                           dtype="bfloat16")
    l.asnumpy()
    t0 = time.time()
    l = trainer.bench_span(steps, (batch, image, image, 3), 1000,
                           dtype="bfloat16")
    l.asnumpy()
    compute_rate = steps * batch / (time.time() - t0)
    log("pure compute (in-graph uint8 + preprocess): %.0f img/s"
        % compute_rate)
    emit("compute_u8span_img_per_sec", compute_rate, "img/s")

    # --- phase 4: wire under compute contention ---
    staged = [0]

    def contender():
        t_end = time.time() + 6.0
        while time.time() < t_end:
            x, _ = xs_host[staged[0] % 16]
            jax.block_until_ready(jax.device_put(x, d))
            staged[0] += 1

    th = threading.Thread(target=contender)
    th.start()
    t0 = time.time()
    while th.is_alive():
        trainer.bench_span(chunk, (batch, image, image, 3), 1000,
                           dtype="bfloat16").asnumpy()
    th.join()
    wire_c_rate = staged[0] * batch / 6.0
    log("wire under compute contention: %.0f img/s" % wire_c_rate)
    emit("wire_contended_img_per_sec", wire_c_rate, "img/s")

    # --- phase 5: data-fed (feeder thread stages device chunks) ---
    stack = jax.jit(lambda *parts: jnp.stack(parts))

    def stage_chunk():
        xs, ys = [], []
        for _ in range(chunk):
            x, y = drain()
            xs.append(jax.device_put(x, d))
            ys.append(y)
        return stack(*xs), np.stack(ys)

    log("compiling step_many (chunk=%d)..." % chunk)
    xc, yc = stage_chunk()
    trainer.step_many(xc, yc)  # compile + warm

    q = queue.Queue(maxsize=2)
    stop = [False]

    def feeder():
        while not stop[0]:
            item = stage_chunk()
            while not stop[0]:
                try:
                    q.put(item, timeout=0.5)
                    break
                except queue.Full:
                    pass

    th = threading.Thread(target=feeder, daemon=True)
    th.start()
    loss = None
    t0 = time.time()
    for _ in range(n_chunks):
        xc, yc = q.get()
        loss = trainer.step_many(xc, yc)  # async dispatch
    loss.asnumpy()
    dt = time.time() - t0
    stop[0] = True
    th.join()          # drain the feeder fully before later phases
    while not q.empty():
        q.get()        # release staged device chunks
    fed_rate = n_chunks * chunk * batch / dt
    bound = min(io_rate, wire_c_rate, compute_rate)
    log("data-fed training: %.0f img/s (binding constraint %.0f img/s -> "
        "pipeline efficiency %.0f%%)"
        % (fed_rate, bound, 100 * fed_rate / bound))
    emit("resnet50_train_datafed_%s_img_per_sec_b%d" % (fmt, batch),
         fed_rate, "img/s",
         vs_baseline=round(fed_rate / BASELINE_IMG_S, 3),
         pipeline_efficiency_vs_bound=round(fed_rate / bound, 3),
         bound="io" if bound == io_rate else
               ("wire_contended" if bound == wire_c_rate else "compute"))

    # --- phase 6: gap-scheduled alternation (round 4) ---
    # Phase 4 proves transfers CANNOT ride alongside in-flight compute on
    # this tunnel (80x collapse: one serialized RPC channel). The best
    # remaining schedule stages the next chunk's device puts in the GAP
    # between dispatches — host decode still overlaps compute (it never
    # touches the device), only the puts serialize:
    #   per chunk: T_wire(idle rate) + T_compute, vs the naive feeder's
    #   T_wire(contended rate) ~= 80x T_wire.
    host_q = queue.Queue(maxsize=2 * chunk)
    stop2 = [False]

    def host_feeder():  # pure host work: safe to overlap compute
        while not stop2[0]:
            item = drain()
            while not stop2[0]:
                try:
                    host_q.put(item, timeout=0.5)
                    break
                except queue.Full:
                    pass

    th2 = threading.Thread(target=host_feeder, daemon=True)
    th2.start()

    def put_chunk():
        xs, ys = [], []
        for _ in range(chunk):
            x, y = host_q.get()
            xs.append(jax.device_put(x, d))
            ys.append(y)
        return stack(*xs), np.stack(ys)

    xc, yc = put_chunk()
    trainer.step_many(xc, yc).asnumpy()  # warm
    t0 = time.time()
    xc, yc = put_chunk()   # chunk 0's puts are part of the measured cost
    for i in range(n_chunks):
        loss = trainer.step_many(xc, yc)   # async dispatch
        if i + 1 < n_chunks:
            # drain the device FIRST so the puts see an idle channel
            loss.asnumpy()
            xc, yc = put_chunk()
    loss.asnumpy()
    dt = time.time() - t0
    stop2[0] = True
    fed_gap = n_chunks * chunk * batch / dt
    # serial-channel model: 1/rate = 1/wire_idle + 1/compute
    model_rate = 1.0 / (1.0 / wire_rate + 1.0 / compute_rate)
    log("data-fed (gap-scheduled): %.0f img/s (serial-channel model "
        "%.0f img/s, %.0f%% of compute)"
        % (fed_gap, model_rate, 100 * fed_gap / compute_rate))
    emit("resnet50_train_datafed_gapsched_%s_img_per_sec_b%d"
         % (fmt, batch), fed_gap, "img/s",
         vs_baseline=round(fed_gap / BASELINE_IMG_S, 3),
         fraction_of_compute=round(fed_gap / compute_rate, 3),
         serial_channel_model_img_per_sec=round(model_rate, 1))

    # --- phase 7: pre-staged device pool ---
    # Measured: the FIRST training dispatch flips this tunnel into a
    # degraded-H2D mode (~150 ms/RPC fixed latency, irreversible — even
    # deleting the trainer doesn't recover it), so no schedule that puts
    # AFTER training starts can feed the chip. But puts BEFORE the first
    # dispatch run at the idle rate, so staging a data pool up front and
    # training from device-resident chunks reaches the full compute rate.
    # A 16 GB HBM holds ~90k uint8 224^2 images alongside ResNet-50
    # training state — the small-dataset epoch-caching strategy.
    # (Pool chunks were NOT donated by step_many: reusable every epoch.)
    if os.environ.get("DF_POOL", "1") != "0":
        n_pool = min(n_chunks, 8)
        pool = []
        t0 = time.time()
        for _ in range(n_pool):
            xs = []
            for _ in range(chunk):
                x, _y = host_q.get() if not host_q.empty() else drain()
                xs.append(jax.device_put(x, d))
            pool.append(jax.block_until_ready(stack(*xs)))
        stage_t = time.time() - t0
        log("NOTE: pool staged AFTER first dispatch here (degraded puts, "
            "%.1fs); in a fresh process staging runs at the idle wire "
            "rate — see PERF.md" % stage_t)
        yd = jax.device_put(jnp.asarray(yc), d)  # labels device-resident
        loss = None
        t0 = time.time()
        for c in range(n_pool):
            loss = trainer.step_many(pool[c], yd)
        loss.asnumpy()
        dt = time.time() - t0
        pool_rate = n_pool * chunk * batch / dt
        log("data-fed (device pool): %.0f img/s (%.0f%% of compute)"
            % (pool_rate, 100 * pool_rate / compute_rate))
        emit("resnet50_train_datafed_devicepool_%s_img_per_sec_b%d"
             % (fmt, batch), pool_rate, "img/s",
             vs_baseline=round(pool_rate / BASELINE_IMG_S, 3),
             fraction_of_compute=round(pool_rate / compute_rate, 3))


if __name__ == "__main__":
    main()
