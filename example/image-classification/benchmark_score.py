"""Inference throughput benchmark across the model zoo.

CLI parity with the reference `example/image-classification/benchmark_score.py`
(the script behind BASELINE.md's inference tables, reference perf.md:194).
TPU-native: each model's forward is functionalized once, jitted as a single
XLA program, and timed with a device->host sync bounding each measurement.

Usage:
  python benchmark_score.py [--model resnet-50] [--batch-size 1,32,64]
                            [--dtype bfloat16] [--image-shape 3,224,224]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # this host's TPU plugin captures JAX_PLATFORMS at interpreter start;
    # only jax.config reliably forces the CPU platform (conftest recipe)
    import jax
    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.parallel.functional import functionalize

# reference benchmark_score.py model list (its get_symbol zoo), mapped to
# the Gluon model zoo constructors
MODELS = {
    "alexnet": vision.alexnet,
    "vgg-16": lambda: vision.get_vgg(16),
    "inception-v3": vision.inception_v3,
    "resnet-50": vision.resnet50_v1,
    "resnet-152": vision.resnet152_v1,
    "squeezenet": vision.squeezenet1_0,
    "mobilenet": vision.mobilenet1_0,
    "mobilenet-v2": vision.mobilenet_v2_1_0,
    "densenet-121": vision.densenet121,
}


def score(model_name, batch, image_shape, dtype, repeat=3, iters=None):
    import jax
    import jax.numpy as jnp

    mx.random.seed(0)
    np.random.seed(0)
    net = MODELS[model_name]()
    net.initialize(mx.init.Xavier())
    c, h, w = image_shape
    net(mx.nd.zeros((1, c, h, w)))
    if dtype == "bfloat16":
        net.cast("bfloat16")
    elif dtype == "int8":
        # real int8 path: conv/dense swapped for int8 blocks with ranges
        # calibrated on one batch (docs/quantization.md)
        from mxnet_tpu.contrib.quantization import quantize_net
        calib = mx.nd.array(np.random.rand(batch, c, h, w)
                            .astype("float32"))
        net = quantize_net(net, calib_data=[calib], calib_mode="naive")
    pure, params = functionalize(net, train=False)
    pvals = [p.data()._data for p in params]
    key = jax.random.PRNGKey(0)

    # image sizes below the model's design resolution can pool down to an
    # EMPTY output tensor, which XLA then rightly dead-codes to nothing —
    # refuse to report a meaningless number
    (probe,), _ = pure(key, pvals, jnp.zeros(
        (1, c, h, w), jnp.bfloat16 if dtype == "bfloat16" else jnp.float32))
    if probe.size == 0:
        raise ValueError(
            "%s produces an empty output at %dx%d — use a larger "
            "--image-shape" % (model_name, h, w))

    if iters is None:
        # the tunneled TPU pays ~0.3s fixed dispatch overhead per call;
        # long spans amortize it (measured: 20 iters -> 1.5K img/s,
        # 400 iters -> 13K+ img/s on the same chip)
        on_tpu = any(d.platform != "cpu" for d in jax.devices())
        iters = 400 if on_tpu else 10

    @jax.jit
    def many(x):
        def body(carry, _):
            (out,), _aux = pure(key, pvals, carry)
            # feed the output back in so XLA cannot dead-code or overlap
            # iterations. NOTE: `0 * mean` or a denormal multiplier is NOT
            # safe — XLA folds provably-non-NaN chains away (verified: int8
            # nets got fully eliminated). 1e-6 keeps a real serial data
            # dependency; the ~1e-6 input drift is irrelevant for timing.
            return carry + 1e-6 * jnp.mean(out).astype(carry.dtype), ()
        final, _ = jax.lax.scan(body, x, None, length=iters)
        return jnp.mean(final)  # scalar D2H sync, not the full batch

    x = jnp.asarray(np.random.rand(batch, c, h, w).astype("float32"))
    if dtype == "bfloat16":
        x = x.astype(jnp.bfloat16)
    np.asarray(many(x))  # compile + warm
    best = 0.0
    for _ in range(repeat):
        t0 = time.time()
        np.asarray(many(x))  # D2H sync bounds the span
        dt = time.time() - t0
        best = max(best, batch * iters / dt)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="all",
                    help="model name or 'all' (%s)" % ",".join(MODELS))
    ap.add_argument("--batch-size", default="1,32",
                    help="comma-separated batch sizes")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16", "int8"])
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--iters", type=int, default=None,
                    help="forwards per compiled span (default: 400 on "
                         "TPU, 10 on CPU)")
    args = ap.parse_args()

    shape = tuple(int(v) for v in args.image_shape.split(","))
    names = list(MODELS) if args.model == "all" else args.model.split(",")
    for name in names:
        for b in (int(v) for v in args.batch_size.split(",")):
            try:
                img_s = score(name, b, shape, args.dtype, iters=args.iters)
            except ValueError as e:  # e.g. empty output at this resolution
                print("model: %s, dtype: %s, batch: %d, SKIPPED (%s)"
                      % (name, args.dtype, b, e), flush=True)
                continue
            print("model: %s, dtype: %s, batch: %d, images/sec: %.2f"
                  % (name, args.dtype, b, img_s), flush=True)


if __name__ == "__main__":
    main()
