/* LeNet training through the GENERATED C++ operator API (role of the
 * reference `cpp-package/example/lenet.cpp`): the ops below (op::
 * Convolution, op::Activation, ...) come from mxtpu_ops.hpp, which
 * gen_ops.cc emitted purely from ABI introspection — nothing here was
 * hand-written per operator.
 *
 * Usage: train_lenet <repo_root>
 * Prints CPP_TRAIN_OK on success (loss drops under SGD). */
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "mxtpu_cpp.hpp"
#include "mxtpu_ops.hpp"

using mxtpu::Executor;
using mxtpu::Invoke;
using mxtpu::KW;
using mxtpu::NDArray;
using mxtpu::Symbol;

int main(int argc, char** argv) {
  mxtpu::Init(argc > 1 ? argv[1] : nullptr);
  MXRandomSeed(11);

  // ---- LeNet-ish on 8x1x12x12, built from GENERATED ops -------------
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  // explicit weight variables, the reference cpp-package/example/lenet.cpp
  // convention (generated signatures expose every required tensor input)
  Symbol c1_w = Symbol::Variable("conv1_weight");
  Symbol f1_w = Symbol::Variable("fc1_weight");
  Symbol f2_w = Symbol::Variable("fc2_weight");
  Symbol c1 = mxtpu::op::Convolution(
      "conv1", data, c1_w, Symbol() /* no bias */,
      {{"num_filter", "8"}, {"kernel", "(3, 3)"}, {"no_bias", "True"}});
  Symbol a1 = mxtpu::op::Activation("act1", c1, {{"act_type", "tanh"}});
  Symbol p1 = mxtpu::op::Pooling(
      "pool1", a1,
      {{"pool_type", "max"}, {"kernel", "(2, 2)"}, {"stride", "(2, 2)"}});
  Symbol fl = mxtpu::op::Flatten("flat", p1);
  Symbol f1 = mxtpu::op::FullyConnected(
      "fc1", fl, f1_w, Symbol(),
      {{"num_hidden", "32"}, {"no_bias", "True"}});
  Symbol a2 = mxtpu::op::Activation("act2", f1, {{"act_type", "relu"}});
  Symbol f2 = mxtpu::op::FullyConnected(
      "fc2", a2, f2_w, Symbol(),
      {{"num_hidden", "10"}, {"no_bias", "True"}});

  // SoftmaxOutput composes (data, label) — both tensor inputs are
  // introspected, so the generated signature takes both
  Symbol net = mxtpu::op::SoftmaxOutput("softmax", f2, label, {});

  const int B = 8, H = 12;
  Executor exec(net, "cpu", "write",
                {{"data", {B, 1, H, H}}, {"softmax_label", {B}}});

  // ---- synthetic data: class = brightest quadrant ---------------------
  std::vector<float> x(B * H * H);
  std::vector<float> y(B);
  unsigned seed = 13;
  auto frand = [&seed]() {
    seed = seed * 1664525u + 1013904223u;
    return static_cast<float>((seed >> 8) & 0xFFFF) / 65536.0f;
  };

  printf("bound\n"); fflush(stdout);
  auto names = exec.ArgNames();
  auto args = exec.ArgArrays();
  auto grads = exec.GradArrays();

  // init params (uniform +-0.2); data/label filled per step
  for (size_t i = 0; i < names.size(); ++i) {
    if (names[i] == "data" || names[i] == "softmax_label") continue;
    auto shape = args[i].Shape();
    int64_t sz = args[i].Size();
    std::vector<float> w(sz);
    for (auto& v : w) v = 0.4f * frand() - 0.2f;
    args[i].CopyFrom(w);
  }

  printf("params inited\n"); fflush(stdout);
  float first_loss = -1.0f, last_loss = -1.0f;
  for (int step = 0; step < 25; ++step) {
    for (int b = 0; b < B; ++b) {
      int cls = step * B + b;
      cls = (cls * 2654435761u >> 4) % 4;
      y[b] = static_cast<float>(cls);
      for (int i = 0; i < H * H; ++i) {
        int r = i / H, c = i % H;
        int q = (r >= H / 2) * 2 + (c >= H / 2);
        x[b * H * H + i] = 0.1f * frand() + (q == cls ? 1.0f : 0.0f);
      }
    }
    for (size_t i = 0; i < names.size(); ++i) {
      if (names[i] == "data") args[i].CopyFrom(x);
      if (names[i] == "softmax_label") args[i].CopyFrom(y);
    }
    if (step == 0) { printf("fwd...\n"); fflush(stdout); }
    exec.Forward(true);
    if (step == 0) { printf("bwd...\n"); fflush(stdout); }
    exec.Backward();
    // SGD via the imperative ABI (lr 0.1, rescale 1/B)
    for (size_t i = 0; i < names.size(); ++i) {
      if (names[i] == "data" || names[i] == "softmax_label") continue;
      if (!grads[i].handle()) continue;
      NDArray scaled = Invoke(
          "_mul_scalar", {grads[i]},
          {{"scalar", std::to_string(0.25 / B)}});
      NDArray upd = Invoke("elemwise_sub", {args[i], scaled});
      args[i].CopyFrom(upd.ToVector());
    }
    // per-example NLL from the softmax outputs
    auto probs = exec.Outputs()[0].ToVector();
    float loss = 0.0f;
    for (int b = 0; b < B; ++b) {
      float p = probs[b * 10 + static_cast<int>(y[b])];
      loss += -logf(p > 1e-9f ? p : 1e-9f);
    }
    loss /= B;
    if (step == 0) first_loss = loss;
    last_loss = loss;
    if (step % 8 == 0) printf("step %2d  loss %.4f\n", step, loss);
  }
  printf("loss %.4f -> %.4f\n", first_loss, last_loss);
  if (!(last_loss < 0.7f * first_loss)) {
    fprintf(stderr, "FAIL: loss did not drop\n");
    return 1;
  }
  printf("CPP_TRAIN_OK\n");
  return 0;
}
