/* C++ binding over the flat C ABI (role of the reference `cpp-package/`:
 * `include/mxnet-cpp/*.h`, which wraps include/mxnet/c_api.h with RAII
 * classes and a code-GENERATED per-operator API, `OpWrapperGenerator.py`).
 *
 * This header is the hand-written core (~230 lines): NDArray / Symbol /
 * Executor RAII wrappers plus the Operator composer. The per-op surface
 * (mxtpu_ops.hpp) is NOT hand-written — `gen_ops.cc` emits it purely from
 * MXSymbolListAtomicSymbolCreators + MXSymbolGetAtomicSymbolInfo, proving
 * the ABI's §2.3 principle: new language bindings are mechanical.
 */
#ifndef MXTPU_CPP_HPP_
#define MXTPU_CPP_HPP_

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxtpu_c.h"

namespace mxtpu {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXGetLastError());
}

inline void Init(const char* repo_root = nullptr) {
  Check(MXTpuInit(repo_root));
}

using KW = std::map<std::string, std::string>;

// ------------------------------------------------------------- NDArray

class NDArray {
 public:
  NDArray() : h_(nullptr) {}
  explicit NDArray(NDArrayHandle h) : h_(h) {}
  NDArray(const std::vector<int64_t>& shape,
          const std::string& dtype = "float32") {
    Check(MXNDArrayCreate(shape.data(), static_cast<int>(shape.size()),
                          dtype.c_str(), &h_));
  }
  NDArray(const std::vector<float>& data,
          const std::vector<int64_t>& shape)
      : NDArray(shape) {
    CopyFrom(data);
  }
  NDArray(const NDArray& o) : h_(o.h_ ? shallow(o.h_) : nullptr) {}
  NDArray& operator=(const NDArray& o) {
    if (this != &o) {
      Free();
      h_ = o.h_ ? shallow(o.h_) : nullptr;
    }
    return *this;
  }
  NDArray(NDArray&& o) noexcept : h_(o.h_) { o.h_ = nullptr; }
  ~NDArray() { Free(); }

  void CopyFrom(const std::vector<float>& data) {
    Check(MXNDArraySyncCopyFromCPU(h_, data.data(),
                                   static_cast<int64_t>(data.size())));
  }
  std::vector<float> ToVector() const {
    std::vector<float> out(Size());
    Check(MXNDArraySyncCopyToCPU(h_, out.data(),
                                 static_cast<int64_t>(out.size())));
    return out;
  }
  std::vector<int64_t> Shape() const {
    int ndim = 0;
    int64_t dims[16];
    Check(MXNDArrayGetShape(h_, &ndim, dims, 16));
    return std::vector<int64_t>(dims, dims + ndim);
  }
  int64_t Size() const {
    int64_t n = 1;
    for (int64_t d : Shape()) n *= d;
    return n;
  }
  NDArrayHandle handle() const { return h_; }

 private:
  static NDArrayHandle shallow(NDArrayHandle h) {
    NDArrayHandle out = nullptr;
    Check(MXShallowCopyNDArray(h, &out));
    return out;
  }
  void Free() {
    if (h_) MXNDArrayFree(h_);
    h_ = nullptr;
  }
  NDArrayHandle h_;
};

// imperative op call: out = op(inputs..., kw)
inline NDArray Invoke(const std::string& op,
                      const std::vector<NDArray>& inputs,
                      const KW& kw = {}) {
  std::string json = "{";
  bool first = true;
  for (const auto& it : kw) {
    if (!first) json += ",";
    first = false;
    // numbers and booleans go in raw so the runtime sees typed values
    // (the imperative path does NOT re-parse strings); everything else
    // is escaped and quoted
    const std::string& v = it.second;
    char* end = nullptr;
    std::strtod(v.c_str(), &end);
    bool numeric = !v.empty() && end && *end == '\0';
    // strtod accepts inf/nan/hex, which are NOT valid JSON: also require
    // the plain decimal character set
    for (char ch : v) {
      if (!isdigit(static_cast<unsigned char>(ch)) && ch != '.' &&
          ch != '-' && ch != '+' && ch != 'e' && ch != 'E') {
        numeric = false;
        break;
      }
    }
    bool boolean = (v == "true" || v == "false");
    if (numeric || boolean) {
      json += "\"" + it.first + "\": " + v;
    } else {
      std::string esc;
      for (char ch : v) {
        if (ch == '"' || ch == '\\') esc += '\\';
        esc += ch;
      }
      json += "\"" + it.first + "\": \"" + esc + "\"";
    }
  }
  json += "}";
  std::vector<NDArrayHandle> in;
  for (const auto& a : inputs) in.push_back(a.handle());
  NDArrayHandle out[8] = {nullptr};
  int num_out = 8;
  Check(MXImperativeInvoke(op.c_str(), in.data(),
                           static_cast<int>(in.size()), json.c_str(), out,
                           &num_out));
  // first output is the result; release the rest (each is an owned ref)
  for (int i = 1; i < num_out; ++i) {
    if (out[i]) MXNDArrayFree(out[i]);
  }
  return NDArray(out[0]);
}

// -------------------------------------------------------------- Symbol

class Symbol {
 public:
  Symbol() : h_(nullptr) {}
  explicit Symbol(SymbolHandle h) : h_(h) {}
  static Symbol Variable(const std::string& name) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }
  Symbol(const Symbol& o) : h_(o.h_ ? shallow(o.h_) : nullptr) {}
  Symbol& operator=(const Symbol& o) {
    if (this != &o) {
      if (h_) MXSymbolFree(h_);
      h_ = o.h_ ? shallow(o.h_) : nullptr;
    }
    return *this;
  }
  ~Symbol() {
    if (h_) MXSymbolFree(h_);
  }

  std::vector<std::string> ListArguments() const {
    int n = 0;
    const char** names = nullptr;
    Check(MXSymbolListArguments(h_, &n, &names));
    return std::vector<std::string>(names, names + n);
  }
  std::string ToJSON() const {
    const char* js = nullptr;
    Check(MXSymbolSaveToJSON(h_, &js));
    return js;
  }
  SymbolHandle handle() const { return h_; }

 private:
  static SymbolHandle shallow(SymbolHandle h) {
    SymbolHandle out = nullptr;
    Check(MXShallowCopySymbol(h, &out));
    return out;
  }
  SymbolHandle h_;
};

// Operator composer: CreateAtomicSymbol + Compose (missing tensor inputs
// become fresh variables named <name>_<arg>, reference convention)
class Operator {
 public:
  explicit Operator(const std::string& op) : op_(op) {}
  Operator& SetParam(const std::string& k, const std::string& v) {
    keys_.push_back(k);
    vals_.push_back(v);
    return *this;
  }
  Operator& AddInput(const Symbol& s) {
    if (s.handle()) inputs_.push_back(s.handle());
    return *this;
  }
  Symbol CreateSymbol(const std::string& name) {
    std::vector<const char*> ck, cv;
    for (auto& k : keys_) ck.push_back(k.c_str());
    for (auto& v : vals_) cv.push_back(v.c_str());
    SymbolHandle out = nullptr;
    Check(MXSymbolCreateAtomicSymbol(op_.c_str(),
                                     static_cast<int>(ck.size()),
                                     ck.data(), cv.data(), &out));
    Symbol owned(out);   // RAII before compose: no leak on compose error
    std::vector<const char*> in_keys(inputs_.size(), nullptr);
    Check(MXSymbolCompose(out, name.c_str(),
                          static_cast<int>(inputs_.size()),
                          in_keys.data(), inputs_.data()));
    return owned;
  }

 private:
  std::string op_;
  std::vector<std::string> keys_, vals_;
  std::vector<SymbolHandle> inputs_;
};

// ------------------------------------------------------------ Executor

class Executor {
 public:
  Executor(const Symbol& sym, const std::string& ctx,
           const std::string& grad_req,
           const std::map<std::string, std::vector<int64_t>>& shapes) {
    std::vector<const char*> keys;
    std::vector<int> ndims;
    std::vector<int64_t> flat;
    for (const auto& it : shapes) {
      keys.push_back(it.first.c_str());
      ndims.push_back(static_cast<int>(it.second.size()));
      flat.insert(flat.end(), it.second.begin(), it.second.end());
    }
    Check(MXExecutorSimpleBindEx(sym.handle(), ctx.c_str(),
                                 grad_req.c_str(),
                                 static_cast<int>(keys.size()),
                                 keys.data(), ndims.data(), flat.data(),
                                 &h_));
  }
  ~Executor() {
    if (h_) MXExecutorFree(h_);
  }
  // owning handle: copying would double-free
  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;
  Executor(Executor&& o) noexcept : h_(o.h_) { o.h_ = nullptr; }

  void Forward(bool is_train) {
    Check(MXExecutorForward(h_, is_train ? 1 : 0));
  }
  void Backward() { Check(MXExecutorBackward(h_, 0, nullptr)); }

  std::vector<NDArray> Outputs() const { return handles("outputs"); }
  std::vector<NDArray> ArgArrays() const { return handles("args"); }
  std::vector<NDArray> GradArrays() const { return handles("grads"); }
  std::vector<std::string> ArgNames() const {
    int n = 0;
    const char** names = nullptr;
    Check(MXExecutorArgNames(h_, &n, &names));
    return std::vector<std::string>(names, names + n);
  }
  ExecutorHandle handle() const { return h_; }

 private:
  std::vector<NDArray> handles(const std::string& which) const {
    int n = 0;
    NDArrayHandle* arr = nullptr;
    if (which == "outputs") {
      Check(MXExecutorOutputs(h_, &n, &arr));
    } else if (which == "args") {
      Check(MXExecutorArgArrays(h_, &n, &arr));
    } else {
      Check(MXExecutorGradArrays(h_, &n, &arr));
    }
    // the ABI returns OWNED references to the executor's LIVE arrays
    // (store_handlelist increfs the originals): wrap them directly, so
    // CopyFrom mutates the bound buffers and grads update after each
    // Backward — a shallow copy here would detach from the executor
    std::vector<NDArray> out;
    for (int i = 0; i < n; ++i) out.emplace_back(NDArray(arr[i]));
    return out;
  }
  ExecutorHandle h_;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_HPP_
